"""Closed-loop serving benchmark — QPS vs p95 latency for SearchServer.

Sweeps client concurrency over a mixed-shape workload (realistic online
traffic: mostly single/small queries, occasional bulk) against one
:class:`raft_tpu.serve.SearchServer`, and reports the headline metric the
serving runtime exists for: **best sustained QPS whose p95 latency fits
the budget** (default 50 ms).

Prints one JSON line per sweep point and ONE final JSON line
``{"metric": "serve_qps_at_p95_budget", "value", "unit", ...}`` in the
``bench.py`` driver format, plus the server's metrics snapshot (queue
depth, batch-fill ratio, compile-cache counters) for the round artifact.

Scale knobs (CPU smoke → TPU record):
  RAFT_BENCH_SERVE_ROWS      index rows            (default 100_000)
  RAFT_BENCH_SERVE_DIM       vector dim            (default 96)
  RAFT_BENCH_SERVE_K         neighbors             (default 10)
  RAFT_BENCH_SERVE_FAMILY    brute_force | ivf_flat (default ivf_flat)
  RAFT_BENCH_SERVE_SECONDS   seconds per sweep point (default 5)
  RAFT_BENCH_SERVE_CLIENTS   comma sweep           (default "1,2,4,8,16")
  RAFT_BENCH_SERVE_BUDGET_MS p95 latency budget    (default 50)
  RAFT_BENCH_SERVE_LADDER    comma bucket ladder   (default "1,8,64")
  RAFT_BENCH_SERVE_SWAPS     swap-under-load phase: rebuild + swap the
                             index this many times while the measured
                             load runs; final JSON gains a "swap" dict
                             (handoffs, drops during handoff, p95 in the
                             window) asserting the zero-drop contract
                             (default 0 = off)
  RAFT_SERVE_FAULTS          arm the chaos injector (see serve.faults)
                             for a smoke of the retry/degrade paths
  RAFT_BENCH_SERVE_RECOVERY  recovery-time mode (replaces the sweep):
                             comma list of WAL record counts; for each,
                             a DurableStore accumulates that many logged
                             mutations past its last snapshot, then
                             crash recovery (restore + replay + first
                             answered query) is timed — the
                             snapshot-cadence sizing curve.  Final JSON
                             metric: serve_recovery_s (ivf_flat only)
  RAFT_BENCH_SERVE_FAILOVER  failover-time mode (replaces the sweep):
                             comma list of WAL tail lengths; for each, a
                             warm standby accumulates that many shipped-
                             but-unapplied records, the primary goes
                             silent, and detection (lease expiry) →
                             promotion (drain + epoch claim + swap) →
                             first answered query on the promoted server
                             is timed — the ack-window sizing curve.
                             Final JSON metric: serve_failover_s
                             (ivf_flat only)
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax  # noqa: E402

from _platform import pin_backend  # noqa: E402

pin_backend(sys.argv)

import numpy as np  # noqa: E402

ROWS = int(os.environ.get("RAFT_BENCH_SERVE_ROWS", 100_000))
DIM = int(os.environ.get("RAFT_BENCH_SERVE_DIM", 96))
K = int(os.environ.get("RAFT_BENCH_SERVE_K", 10))
FAMILY = os.environ.get("RAFT_BENCH_SERVE_FAMILY", "ivf_flat")
SECONDS = float(os.environ.get("RAFT_BENCH_SERVE_SECONDS", 5))
CLIENTS = tuple(int(c) for c in
                os.environ.get("RAFT_BENCH_SERVE_CLIENTS",
                               "1,2,4,8,16").split(","))
BUDGET_MS = float(os.environ.get("RAFT_BENCH_SERVE_BUDGET_MS", 50))
LADDER = tuple(int(b) for b in
               os.environ.get("RAFT_BENCH_SERVE_LADDER", "1,8,64").split(","))
SWAPS = int(os.environ.get("RAFT_BENCH_SERVE_SWAPS", 0))
RECOVERY = os.environ.get("RAFT_BENCH_SERVE_RECOVERY", "")
FAILOVER = os.environ.get("RAFT_BENCH_SERVE_FAILOVER", "")

# the mixed-shape request mix: point lookups dominate, small batches
# common, bulk occasional — the traffic the bucket ladder is shaped for
_SHAPES = (1, 1, 1, 2, 4, 8, 8, 16, 32, 64)


def _build_index(db):
    if FAMILY == "brute_force":
        import jax.numpy as jnp

        return jnp.asarray(db), None
    if FAMILY == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat

        n_lists = max(8, int(np.sqrt(ROWS)))
        idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=n_lists))
        return idx, ivf_flat.IvfFlatSearchParams(
            n_probes=max(1, n_lists // 16))
    raise SystemExit(f"unknown RAFT_BENCH_SERVE_FAMILY={FAMILY!r}")


def _sweep_point(srv, n_clients: int, seconds: float, rng_seed: int):
    """Closed loop: each client thread submits, waits, resubmits, for
    ``seconds``.  Returns (qps, p95_ms, snapshot-delta)."""
    stop = threading.Event()
    done = [0] * n_clients
    lat0 = srv.metrics.snapshot()

    def client(j):
        rng = np.random.default_rng(rng_seed + j)
        while not stop.is_set():
            rows = int(rng.choice(_SHAPES))
            q = rng.standard_normal((rows, DIM)).astype(np.float32)
            try:
                srv.submit(q, deadline_ms=10 * BUDGET_MS).result(timeout=30)
                done[j] += 1
            except Exception:
                pass  # rejections are counted by the server's metrics

    threads = [threading.Thread(target=client, args=(j,), daemon=True)
               for j in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    dt = time.perf_counter() - t0
    snap = srv.metrics.snapshot()
    return (sum(done) / dt, snap["latency_ms"]["p95"],
            {"completed_delta": snap["completed"] - lat0["completed"],
             "rejected_deadline_delta":
                 snap["rejected_deadline"] - lat0["rejected_deadline"]})


def _swap_phase(srv, db, n_clients: int, n_swaps: int, seconds: float):
    """Swap-under-load: keep a closed-loop client load running while the
    index is rebuilt (rows permuted — same shapes, new generation) and
    swapped ``n_swaps`` times.  Client-side latencies are collected so
    the reported p95 covers exactly the handoff window; any client-visible
    failure counts as a drop (the contract is zero)."""
    stop = threading.Event()
    lat_ms: list = []
    drops = [0] * n_clients
    lock = threading.Lock()
    snap0 = srv.metrics.snapshot()
    compiles0 = srv.cache.compiles

    def client(j):
        rng = np.random.default_rng(1000 + j)
        while not stop.is_set():
            rows = int(rng.choice(_SHAPES))
            q = rng.standard_normal((rows, DIM)).astype(np.float32)
            t0 = time.perf_counter()
            try:
                srv.submit(q, deadline_ms=10 * BUDGET_MS).result(timeout=30)
                with lock:
                    lat_ms.append(1e3 * (time.perf_counter() - t0))
            except Exception:
                drops[j] += 1

    threads = [threading.Thread(target=client, args=(j,), daemon=True)
               for j in range(n_clients)]
    for t in threads:
        t.start()
    gap = seconds / max(1, n_swaps)
    swap_s = []
    rng = np.random.default_rng(99)
    for _ in range(n_swaps):
        time.sleep(gap / 2)
        t0 = time.perf_counter()
        new_index, _ = _build_index(db[rng.permutation(db.shape[0])])
        build_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        srv.swap_index(new_index)
        swap_s.append(time.perf_counter() - t0)
        time.sleep(gap / 2)
        print(json.dumps({"config": "serve_swap",
                          "generation": srv.generation,
                          "build_s": round(build_s, 2),
                          "swap_s": round(swap_s[-1], 4)}), flush=True)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    snap = srv.metrics.snapshot()
    lat_ms.sort()
    return {
        "swaps": n_swaps,
        "clients": n_clients,
        "completed": snap["completed"] - snap0["completed"],
        "dropped": sum(drops)
        + snap["rejected_deadline"] - snap0["rejected_deadline"]
        + snap["faulted_batches"] - snap0["faulted_batches"],
        "p95_ms_during_handoff": round(
            lat_ms[int(0.95 * (len(lat_ms) - 1))], 3) if lat_ms else None,
        "swap_s_max": round(max(swap_s), 4) if swap_s else None,
        "recompiles": srv.cache.compiles - compiles0,
        "retries": snap["retries"] - snap0["retries"],
    }


def run_recovery(spec: str = RECOVERY) -> dict:
    """Crash-recovery timing: for each WAL length in ``spec`` (comma
    list of record counts past the last snapshot), build a durable
    ivf_flat deployment, accumulate that many logged mutations, and time
    ``SearchServer.recover`` → first answered query.  The curve is the
    snapshot-cadence sizing tool: restore cost is ~flat (snapshot load),
    replay cost grows with the tail you allow between snapshots."""
    import shutil
    import tempfile

    from raft_tpu.neighbors import ivf_flat, mutation
    from raft_tpu.neighbors.wal import DurableStore
    from raft_tpu.serve import SearchServer, ServerConfig

    if FAMILY != "ivf_flat":
        raise SystemExit("recovery mode mutates online: ivf_flat only")
    tails = tuple(int(p) for p in spec.split(","))
    rng = np.random.default_rng(0)
    db = rng.standard_normal((ROWS, DIM)).astype(np.float32)
    index, params = _build_index(db)
    live = mutation.delete(index, [0], id_space=2 * ROWS)
    queries = rng.standard_normal((8, DIM)).astype(np.float32)
    points = []
    for tail in tails:
        root = tempfile.mkdtemp(prefix="raft-bench-recovery-")
        try:
            store = DurableStore.create(root, live)
            for r in range(tail):  # the mutation workload past the snapshot
                if r % 4 == 3:
                    store.delete(rng.integers(0, ROWS, 2))
                else:
                    store.extend(
                        rng.standard_normal((64, DIM)).astype(np.float32))
            store.close()
            wal_bytes = os.path.getsize(os.path.join(root, "wal.log"))
            t0 = time.perf_counter()
            srv = SearchServer.recover(root, k=K, params=params,
                                       config=ServerConfig(ladder=LADDER))
            restore_s = time.perf_counter() - t0
            srv.search(queries)  # step()-driven: no thread needed
            ready_s = time.perf_counter() - t0
            point = {"config": "serve_recovery", "wal_records": tail,
                     "wal_mib": round(wal_bytes / 2**20, 2),
                     "restore_s": round(restore_s, 3),
                     "ready_s": round(ready_s, 3),
                     "replayed": srv.metrics.wal_replayed}
            srv.durable_store.close()
        finally:
            shutil.rmtree(root, ignore_errors=True)
        points.append(point)
        print(json.dumps(point), flush=True)
    final = {
        "metric": "serve_recovery_s",
        "value": points[-1]["ready_s"],
        "unit": f"s@{tails[-1]}walrecords",
        "family": FAMILY, "rows": ROWS, "dim": DIM, "k": K,
        "backend": jax.default_backend(),
        "points": points,
    }
    print(json.dumps(final), flush=True)
    return final


def run_failover(spec: str = FAILOVER) -> dict:
    """Failover timing: for each WAL tail length in ``spec``, replicate
    a primary into a warm standby, pile that many shipped-but-unapplied
    records in the ship queue, silence the primary, and time detection
    (lease expiry) → promotion (drain + fenced epoch claim + generation
    swap) → first answered query on the promoted server.  The curve
    sizes the async ack window: a longer allowed tail is cheaper per
    write but every queued record lands on the promotion drain path."""
    import shutil
    import tempfile

    from raft_tpu.neighbors import mutation
    from raft_tpu.neighbors.wal import DurableStore
    from raft_tpu.serve import (LogShipper, QueuePair, ReplicationConfig,
                                SearchServer, ServerConfig, StandbyReplica)

    if FAMILY != "ivf_flat":
        raise SystemExit("failover mode mutates online: ivf_flat only")
    tails = tuple(int(p) for p in spec.split(","))
    rng = np.random.default_rng(0)
    db = rng.standard_normal((ROWS, DIM)).astype(np.float32)
    index, params = _build_index(db)
    live = mutation.delete(index, [0], id_space=2 * ROWS)
    queries = rng.standard_normal((8, DIM)).astype(np.float32)
    points = []
    for tail in tails:
        proot = tempfile.mkdtemp(prefix="raft-bench-failover-p-")
        sroot = tempfile.mkdtemp(prefix="raft-bench-failover-s-")
        try:
            # async with a window past the tail: shipping never blocks,
            # the whole tail is queued when the primary dies; refresh is
            # deferred so the drain applies records, not swaps
            cfg = ReplicationConfig(ack_mode="async", ship_queue=tail + 8,
                                    lease_s=0.05, refresh_every=1 << 30)
            a, b = QueuePair.create()
            store = DurableStore.create(proot, live)
            shipper = LogShipper(store, a, config=cfg)
            replica = StandbyReplica(sroot, b, config=cfg)
            shipper.pump()   # hello -> cold snapshot bootstrap
            replica.poll()   # standby warm at the snapshot watermark
            shipper.pump()
            ssrv = SearchServer(replica.store.index, k=K, params=params,
                                config=ServerConfig(ladder=LADDER))
            replica.attach_server(ssrv)
            ssrv.warmup()    # the standby was already serving reads
            for r in range(tail):  # the shipped-but-unapplied tail
                if r % 4 == 3:
                    store.delete(rng.integers(0, ROWS, 2))
                else:
                    store.extend(
                        rng.standard_normal((64, DIM)).astype(np.float32))
            wal_bytes = os.path.getsize(os.path.join(proot, "wal.log"))
            # ---- the primary dies here -------------------------------
            replica.last_beat = replica.clock()  # last heartbeat heard
            t0 = time.perf_counter()
            while replica.primary_alive():
                time.sleep(cfg.lease_s / 10)
            t_detect = time.perf_counter()
            replica.promote(drain_timeout_s=0.0)
            t_promote = time.perf_counter()
            ssrv.search(queries)  # step()-driven: no thread needed
            t_reply = time.perf_counter()
            point = {"config": "serve_failover", "wal_tail": tail,
                     "wal_mib": round(wal_bytes / 2**20, 2),
                     "detect_s": round(t_detect - t0, 3),
                     "promote_s": round(t_promote - t_detect, 3),
                     "first_reply_s": round(t_reply - t_promote, 3),
                     "total_s": round(t_reply - t0, 3),
                     "applied": replica.applied,
                     "primary_lsn": store.wal_lsn,
                     "epoch": replica.fence.epoch}
            assert replica.applied == store.wal_lsn, \
                "promotion drain lost queued records"
            replica.store.close()
            store.close()
        finally:
            shutil.rmtree(proot, ignore_errors=True)
            shutil.rmtree(sroot, ignore_errors=True)
        points.append(point)
        print(json.dumps(point), flush=True)
    final = {
        "metric": "serve_failover_s",
        "value": points[-1]["total_s"],
        "unit": f"s@{tails[-1]}waltail",
        "family": FAMILY, "rows": ROWS, "dim": DIM, "k": K,
        "lease_s": 0.05,
        "backend": jax.default_backend(),
        "points": points,
    }
    print(json.dumps(final), flush=True)
    return final


def run(seconds: float = SECONDS, clients=CLIENTS) -> dict:
    """Build index, start server, sweep concurrency; returns the final
    result dict (also printed as the last JSON line)."""
    from raft_tpu.serve import SearchServer, ServerConfig

    rng = np.random.default_rng(0)
    db = rng.standard_normal((ROWS, DIM)).astype(np.float32)
    index, params = _build_index(db)
    cfg = ServerConfig(ladder=LADDER, max_wait_ms=1.0,
                       max_queue=max(256, 32 * max(clients)))
    srv = SearchServer(index, k=K, params=params, config=cfg)
    t0 = time.perf_counter()
    n_exec = srv.warmup()
    compile_s = time.perf_counter() - t0
    print(json.dumps({"config": "serve_warmup", "family": srv.family,
                      "executables": n_exec,
                      "compile_s": round(compile_s, 2)}), flush=True)
    srv.start(warmup=False)

    best = {"qps": 0.0, "p95_ms": None, "clients": 0}
    points = []
    try:
        for n in clients:
            qps, p95, extra = _sweep_point(srv, n, seconds, rng_seed=17 * n)
            point = {"config": "serve_sweep", "clients": n,
                     "qps": round(qps, 1), "p95_ms": p95, **extra}
            points.append(point)
            print(json.dumps(point), flush=True)
            if p95 <= BUDGET_MS and qps > best["qps"]:
                best = {"qps": qps, "p95_ms": p95, "clients": n}
        swap = None
        if SWAPS:
            swap = _swap_phase(srv, db, best["clients"] or max(clients),
                               SWAPS, seconds)
            print(json.dumps({"config": "serve_swap_phase", **swap}),
                  flush=True)
    finally:
        srv.stop()

    snap = srv.metrics_snapshot()
    final = {
        "metric": "serve_qps_at_p95_budget",
        "value": round(best["qps"], 1),
        "unit": f"qps@p95<={BUDGET_MS:g}ms",
        "clients": best["clients"],
        "p95_ms": best["p95_ms"],
        "family": srv.family,
        "rows": ROWS, "dim": DIM, "k": K, "ladder": list(srv.ladder),
        "backend": jax.default_backend(),
        "points": points,
        "serving_metrics": snap,
    }
    if SWAPS:
        final["swap"] = swap
    print(json.dumps(final), flush=True)
    return final


if __name__ == "__main__":
    if RECOVERY:
        run_recovery()
    elif FAILOVER:
        run_failover()
    else:
        run()
