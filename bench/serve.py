"""Closed-loop serving benchmark — QPS vs p95 latency for SearchServer.

Sweeps client concurrency over a mixed-shape workload (realistic online
traffic: mostly single/small queries, occasional bulk) against one
:class:`raft_tpu.serve.SearchServer`, and reports the headline metric the
serving runtime exists for: **best sustained QPS whose p95 latency fits
the budget** (default 50 ms).

Prints one JSON line per sweep point and ONE final JSON line
``{"metric": "serve_qps_at_p95_budget", "value", "unit", ...}`` in the
``bench.py`` driver format, plus the server's metrics snapshot (queue
depth, batch-fill ratio, compile-cache counters) for the round artifact.

Scale knobs (CPU smoke → TPU record):
  RAFT_BENCH_SERVE_ROWS      index rows            (default 100_000)
  RAFT_BENCH_SERVE_DIM       vector dim            (default 96)
  RAFT_BENCH_SERVE_K         neighbors             (default 10)
  RAFT_BENCH_SERVE_FAMILY    brute_force | ivf_flat (default ivf_flat)
  RAFT_BENCH_SERVE_SECONDS   seconds per sweep point (default 5)
  RAFT_BENCH_SERVE_CLIENTS   comma sweep           (default "1,2,4,8,16")
  RAFT_BENCH_SERVE_BUDGET_MS p95 latency budget    (default 50)
  RAFT_BENCH_SERVE_LADDER    comma bucket ladder   (default "1,8,64")
  RAFT_BENCH_SERVE_SWAPS     swap-under-load phase: rebuild + swap the
                             index this many times while the measured
                             load runs; final JSON gains a "swap" dict
                             (handoffs, drops during handoff, p95 in the
                             window) asserting the zero-drop contract
                             (default 0 = off)
  RAFT_SERVE_FAULTS          arm the chaos injector (see serve.faults)
                             for a smoke of the retry/degrade paths
  RAFT_BENCH_SERVE_RECOVERY  recovery-time mode (replaces the sweep):
                             comma list of WAL record counts; for each,
                             a DurableStore accumulates that many logged
                             mutations past its last snapshot, then
                             crash recovery (restore + replay + first
                             answered query) is timed — the
                             snapshot-cadence sizing curve.  Final JSON
                             metric: serve_recovery_s (ivf_flat only)
  RAFT_BENCH_SERVE_REPLICAS  fleet mode (replaces the sweep): comma list
                             of replica counts (e.g. "1,2,4"); each
                             point spawns that many WORKER SUBPROCESSES
                             (own interpreter, own SearchServer), wires
                             them to this coordinator over the
                             replication wire protocol (SocketListener /
                             SocketTransport + encode/decode_message),
                             and drives a closed loop through a least-
                             outstanding router — aggregate QPS@p95 vs
                             replica count, plus a SIGKILL drill at 2
                             replicas asserting the router sheds to the
                             survivor with ZERO dropped in-deadline
                             requests.  Final JSON metric:
                             serve_fleet_qps_at_p95_budget, with the
                             2-vs-1 scaling ratio asserted >= 1.6x at
                             unchanged p95 (the ISSUE 16 ratchet).
                             Replicas here are processes on one host;
                             on a pod each worker is one accelerator
                             host running the same protocol.
  RAFT_BENCH_SERVE_FLEET_CLIENTS   closed-loop clients per replica
                             (default 6 — under the smallest >1 ladder
                             bucket, so the batcher's hold-open window,
                             not single-core compute, sets the cadence
                             and replicas overlap their windows)
  RAFT_BENCH_SERVE_FLEET_WAIT_MS   per-replica batching window in fleet
                             mode (default 15 ms: wait-dominated on
                             purpose — the sweep measures fan-out
                             scaling, and the window is what an online
                             pod trades for batch fill anyway)
  RAFT_BENCH_SERVE_FAILOVER  failover-time mode (replaces the sweep):
                             comma list of WAL tail lengths; for each, a
                             warm standby accumulates that many shipped-
                             but-unapplied records, the primary goes
                             silent, and detection (lease expiry) →
                             promotion (drain + epoch claim + swap) →
                             first answered query on the promoted server
                             is timed — the ack-window sizing curve.
                             Final JSON metric: serve_failover_s
                             (ivf_flat only)
"""

from __future__ import annotations

import itertools
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax  # noqa: E402

from _platform import pin_backend  # noqa: E402

pin_backend(sys.argv)

import numpy as np  # noqa: E402

ROWS = int(os.environ.get("RAFT_BENCH_SERVE_ROWS", 100_000))
DIM = int(os.environ.get("RAFT_BENCH_SERVE_DIM", 96))
K = int(os.environ.get("RAFT_BENCH_SERVE_K", 10))
FAMILY = os.environ.get("RAFT_BENCH_SERVE_FAMILY", "ivf_flat")
SECONDS = float(os.environ.get("RAFT_BENCH_SERVE_SECONDS", 5))
CLIENTS = tuple(int(c) for c in
                os.environ.get("RAFT_BENCH_SERVE_CLIENTS",
                               "1,2,4,8,16").split(","))
BUDGET_MS = float(os.environ.get("RAFT_BENCH_SERVE_BUDGET_MS", 50))
LADDER = tuple(int(b) for b in
               os.environ.get("RAFT_BENCH_SERVE_LADDER", "1,8,64").split(","))
SWAPS = int(os.environ.get("RAFT_BENCH_SERVE_SWAPS", 0))
RECOVERY = os.environ.get("RAFT_BENCH_SERVE_RECOVERY", "")
FAILOVER = os.environ.get("RAFT_BENCH_SERVE_FAILOVER", "")
REPLICAS = os.environ.get("RAFT_BENCH_SERVE_REPLICAS", "")
FLEET_CLIENTS = int(os.environ.get("RAFT_BENCH_SERVE_FLEET_CLIENTS", 6))
FLEET_WAIT_MS = float(os.environ.get("RAFT_BENCH_SERVE_FLEET_WAIT_MS", 15.0))

# the mixed-shape request mix: point lookups dominate, small batches
# common, bulk occasional — the traffic the bucket ladder is shaped for
_SHAPES = (1, 1, 1, 2, 4, 8, 8, 16, 32, 64)

# fleet mode measures the interactive tier only: point lookups + pairs,
# kept under the top ladder bucket so each replica's cadence is its
# batcher's hold-open window (the thing replicas overlap) rather than
# bulk-batch compute, which belongs to the single-server sweep above
_FLEET_SHAPES = (1, 1, 1, 2)


def _build_index(db):
    if FAMILY == "brute_force":
        import jax.numpy as jnp

        return jnp.asarray(db), None
    if FAMILY == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat

        n_lists = max(8, int(np.sqrt(ROWS)))
        idx = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(n_lists=n_lists))
        return idx, ivf_flat.IvfFlatSearchParams(
            n_probes=max(1, n_lists // 16))
    raise SystemExit(f"unknown RAFT_BENCH_SERVE_FAMILY={FAMILY!r}")


def _sweep_point(srv, n_clients: int, seconds: float, rng_seed: int):
    """Closed loop: each client thread submits, waits, resubmits, for
    ``seconds``.  Returns (qps, p95_ms, snapshot-delta)."""
    stop = threading.Event()
    done = [0] * n_clients
    lat0 = srv.metrics.snapshot()

    def client(j):
        rng = np.random.default_rng(rng_seed + j)
        while not stop.is_set():
            rows = int(rng.choice(_SHAPES))
            q = rng.standard_normal((rows, DIM)).astype(np.float32)
            try:
                srv.submit(q, deadline_ms=10 * BUDGET_MS).result(timeout=30)
                done[j] += 1
            except Exception:
                pass  # rejections are counted by the server's metrics

    threads = [threading.Thread(target=client, args=(j,), daemon=True)
               for j in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    dt = time.perf_counter() - t0
    snap = srv.metrics.snapshot()
    return (sum(done) / dt, snap["latency_ms"]["p95"],
            {"completed_delta": snap["completed"] - lat0["completed"],
             "rejected_deadline_delta":
                 snap["rejected_deadline"] - lat0["rejected_deadline"]})


def _swap_phase(srv, db, n_clients: int, n_swaps: int, seconds: float):
    """Swap-under-load: keep a closed-loop client load running while the
    index is rebuilt (rows permuted — same shapes, new generation) and
    swapped ``n_swaps`` times.  Client-side latencies are collected so
    the reported p95 covers exactly the handoff window; any client-visible
    failure counts as a drop (the contract is zero)."""
    stop = threading.Event()
    lat_ms: list = []
    drops = [0] * n_clients
    lock = threading.Lock()
    snap0 = srv.metrics.snapshot()
    compiles0 = srv.cache.compiles

    def client(j):
        rng = np.random.default_rng(1000 + j)
        while not stop.is_set():
            rows = int(rng.choice(_SHAPES))
            q = rng.standard_normal((rows, DIM)).astype(np.float32)
            t0 = time.perf_counter()
            try:
                srv.submit(q, deadline_ms=10 * BUDGET_MS).result(timeout=30)
                with lock:
                    lat_ms.append(1e3 * (time.perf_counter() - t0))
            except Exception:
                drops[j] += 1

    threads = [threading.Thread(target=client, args=(j,), daemon=True)
               for j in range(n_clients)]
    for t in threads:
        t.start()
    gap = seconds / max(1, n_swaps)
    swap_s = []
    rng = np.random.default_rng(99)
    for _ in range(n_swaps):
        time.sleep(gap / 2)
        t0 = time.perf_counter()
        new_index, _ = _build_index(db[rng.permutation(db.shape[0])])
        build_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        srv.swap_index(new_index)
        swap_s.append(time.perf_counter() - t0)
        time.sleep(gap / 2)
        print(json.dumps({"config": "serve_swap",
                          "generation": srv.generation,
                          "build_s": round(build_s, 2),
                          "swap_s": round(swap_s[-1], 4)}), flush=True)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    snap = srv.metrics.snapshot()
    lat_ms.sort()
    return {
        "swaps": n_swaps,
        "clients": n_clients,
        "completed": snap["completed"] - snap0["completed"],
        "dropped": sum(drops)
        + snap["rejected_deadline"] - snap0["rejected_deadline"]
        + snap["faulted_batches"] - snap0["faulted_batches"],
        "p95_ms_during_handoff": round(
            lat_ms[int(0.95 * (len(lat_ms) - 1))], 3) if lat_ms else None,
        "swap_s_max": round(max(swap_s), 4) if swap_s else None,
        "recompiles": srv.cache.compiles - compiles0,
        "retries": snap["retries"] - snap0["retries"],
    }


def run_recovery(spec: str = RECOVERY) -> dict:
    """Crash-recovery timing: for each WAL length in ``spec`` (comma
    list of record counts past the last snapshot), build a durable
    ivf_flat deployment, accumulate that many logged mutations, and time
    ``SearchServer.recover`` → first answered query.  The curve is the
    snapshot-cadence sizing tool: restore cost is ~flat (snapshot load),
    replay cost grows with the tail you allow between snapshots."""
    import shutil
    import tempfile

    from raft_tpu.neighbors import ivf_flat, mutation
    from raft_tpu.neighbors.wal import DurableStore
    from raft_tpu.serve import SearchServer, ServerConfig

    if FAMILY != "ivf_flat":
        raise SystemExit("recovery mode mutates online: ivf_flat only")
    tails = tuple(int(p) for p in spec.split(","))
    rng = np.random.default_rng(0)
    db = rng.standard_normal((ROWS, DIM)).astype(np.float32)
    index, params = _build_index(db)
    live = mutation.delete(index, [0], id_space=2 * ROWS)
    queries = rng.standard_normal((8, DIM)).astype(np.float32)
    points = []
    for tail in tails:
        root = tempfile.mkdtemp(prefix="raft-bench-recovery-")
        try:
            store = DurableStore.create(root, live)
            for r in range(tail):  # the mutation workload past the snapshot
                if r % 4 == 3:
                    store.delete(rng.integers(0, ROWS, 2))
                else:
                    store.extend(
                        rng.standard_normal((64, DIM)).astype(np.float32))
            store.close()
            wal_bytes = os.path.getsize(os.path.join(root, "wal.log"))
            t0 = time.perf_counter()
            srv = SearchServer.recover(root, k=K, params=params,
                                       config=ServerConfig(ladder=LADDER))
            restore_s = time.perf_counter() - t0
            srv.search(queries)  # step()-driven: no thread needed
            ready_s = time.perf_counter() - t0
            point = {"config": "serve_recovery", "wal_records": tail,
                     "wal_mib": round(wal_bytes / 2**20, 2),
                     "restore_s": round(restore_s, 3),
                     "ready_s": round(ready_s, 3),
                     "replayed": srv.metrics.wal_replayed}
            srv.durable_store.close()
        finally:
            shutil.rmtree(root, ignore_errors=True)
        points.append(point)
        print(json.dumps(point), flush=True)
    final = {
        "metric": "serve_recovery_s",
        "value": points[-1]["ready_s"],
        "unit": f"s@{tails[-1]}walrecords",
        "family": FAMILY, "rows": ROWS, "dim": DIM, "k": K,
        "backend": jax.default_backend(),
        "points": points,
    }
    print(json.dumps(final), flush=True)
    return final


def run_failover(spec: str = FAILOVER) -> dict:
    """Failover timing: for each WAL tail length in ``spec``, replicate
    a primary into a warm standby, pile that many shipped-but-unapplied
    records in the ship queue, silence the primary, and time detection
    (lease expiry) → promotion (drain + fenced epoch claim + generation
    swap) → first answered query on the promoted server.  The curve
    sizes the async ack window: a longer allowed tail is cheaper per
    write but every queued record lands on the promotion drain path."""
    import shutil
    import tempfile

    from raft_tpu.neighbors import mutation
    from raft_tpu.neighbors.wal import DurableStore
    from raft_tpu.serve import (LogShipper, QueuePair, ReplicationConfig,
                                SearchServer, ServerConfig, StandbyReplica)

    if FAMILY != "ivf_flat":
        raise SystemExit("failover mode mutates online: ivf_flat only")
    tails = tuple(int(p) for p in spec.split(","))
    rng = np.random.default_rng(0)
    db = rng.standard_normal((ROWS, DIM)).astype(np.float32)
    index, params = _build_index(db)
    live = mutation.delete(index, [0], id_space=2 * ROWS)
    queries = rng.standard_normal((8, DIM)).astype(np.float32)
    points = []
    for tail in tails:
        proot = tempfile.mkdtemp(prefix="raft-bench-failover-p-")
        sroot = tempfile.mkdtemp(prefix="raft-bench-failover-s-")
        try:
            # async with a window past the tail: shipping never blocks,
            # the whole tail is queued when the primary dies; refresh is
            # deferred so the drain applies records, not swaps
            cfg = ReplicationConfig(ack_mode="async", ship_queue=tail + 8,
                                    lease_s=0.05, refresh_every=1 << 30)
            a, b = QueuePair.create()
            store = DurableStore.create(proot, live)
            shipper = LogShipper(store, a, config=cfg)
            replica = StandbyReplica(sroot, b, config=cfg)
            shipper.pump()   # hello -> cold snapshot bootstrap
            replica.poll()   # standby warm at the snapshot watermark
            shipper.pump()
            ssrv = SearchServer(replica.store.index, k=K, params=params,
                                config=ServerConfig(ladder=LADDER))
            replica.attach_server(ssrv)
            ssrv.warmup()    # the standby was already serving reads
            for r in range(tail):  # the shipped-but-unapplied tail
                if r % 4 == 3:
                    store.delete(rng.integers(0, ROWS, 2))
                else:
                    store.extend(
                        rng.standard_normal((64, DIM)).astype(np.float32))
            wal_bytes = os.path.getsize(os.path.join(proot, "wal.log"))
            # ---- the primary dies here -------------------------------
            replica.last_beat = replica.clock()  # last heartbeat heard
            t0 = time.perf_counter()
            while replica.primary_alive():
                time.sleep(cfg.lease_s / 10)
            t_detect = time.perf_counter()
            replica.promote(drain_timeout_s=0.0)
            t_promote = time.perf_counter()
            ssrv.search(queries)  # step()-driven: no thread needed
            t_reply = time.perf_counter()
            point = {"config": "serve_failover", "wal_tail": tail,
                     "wal_mib": round(wal_bytes / 2**20, 2),
                     "detect_s": round(t_detect - t0, 3),
                     "promote_s": round(t_promote - t_detect, 3),
                     "first_reply_s": round(t_reply - t_promote, 3),
                     "total_s": round(t_reply - t0, 3),
                     "applied": replica.applied,
                     "primary_lsn": store.wal_lsn,
                     "epoch": replica.fence.epoch}
            assert replica.applied == store.wal_lsn, \
                "promotion drain lost queued records"
            replica.store.close()
            store.close()
        finally:
            shutil.rmtree(proot, ignore_errors=True)
            shutil.rmtree(sroot, ignore_errors=True)
        points.append(point)
        print(json.dumps(point), flush=True)
    final = {
        "metric": "serve_failover_s",
        "value": points[-1]["total_s"],
        "unit": f"s@{tails[-1]}waltail",
        "family": FAMILY, "rows": ROWS, "dim": DIM, "k": K,
        "lease_s": 0.05,
        "backend": jax.default_backend(),
        "points": points,
    }
    print(json.dumps(final), flush=True)
    return final


# -- fleet mode: subprocess replicas behind a coordinator router --------
#
# The wire protocol is the replication stack's own framing
# (encode_message / decode_message over SocketTransport — CRC-checked,
# torn-frame-safe), with three request kinds:
#   fleet_search  coordinator -> worker   {q} + req_id, deadline_ms
#   fleet_reply   worker -> coordinator   req_id, ok [, err]
#   fleet_quit / fleet_bye                orderly shutdown + final stats
# Replies carry only ok/err back to the closed loop (the coordinator
# times the round trip; it does not re-verify payloads the serve suite
# already pins bit-identical), but dist/ids ride along so the drill is
# an end-to-end answer, not an ack.


def run_fleet_worker() -> None:
    """One replica process: build the same index every replica builds
    (same seed — replicas are peers, not shards), serve it through a
    SearchServer, and answer coordinator frames until quit/EOF."""
    import queue as queue_mod

    from raft_tpu.serve import SearchServer, ServerConfig, SocketTransport
    from raft_tpu.serve.replication import encode_message

    name = os.environ["RAFT_BENCH_FLEET_NAME"]
    port = int(os.environ["RAFT_BENCH_FLEET_PORT"])
    rng = np.random.default_rng(0)
    db = rng.standard_normal((ROWS, DIM)).astype(np.float32)
    index, params = _build_index(db)
    cfg = ServerConfig(ladder=LADDER, max_wait_ms=FLEET_WAIT_MS,
                       max_queue=max(256, 32 * FLEET_CLIENTS))
    srv = SearchServer(index, k=K, params=params, config=cfg)
    srv.start()  # ladder warmed before hello: startup is not measured
    link = SocketTransport.connect("127.0.0.1", port)
    link.send(encode_message("fleet_hello", name=name, pid=os.getpid()))

    work: "queue_mod.Queue" = queue_mod.Queue()

    def handle() -> None:
        while True:
            item = work.get()
            if item is None:
                return
            rid, q, deadline_ms = item
            try:
                d, i = srv.submit(q, deadline_ms=deadline_ms).result(
                    timeout=30)
                link.send(encode_message(
                    "fleet_reply",
                    {"dist": np.asarray(jax.device_get(d)),
                     "ids": np.asarray(jax.device_get(i))},
                    req_id=rid, ok=True))
            except OSError:
                return  # coordinator gone: nothing to reply to
            except Exception as e:  # rejection crosses the wire as a name
                try:
                    link.send(encode_message("fleet_reply", req_id=rid,
                                             ok=False,
                                             err=type(e).__name__))
                except OSError:
                    return

    pool = [threading.Thread(target=handle, daemon=True) for _ in range(8)]
    for t in pool:
        t.start()
    try:
        while True:
            msg = link.recv(timeout=1.0)
            if msg is None:
                if link.closed:
                    break  # coordinator died: exit quietly
                continue
            if msg.kind == "fleet_search":
                work.put((msg.static["req_id"], msg.arrays["q"],
                          msg.static.get("deadline_ms")))
            elif msg.kind == "fleet_quit":
                break
    finally:
        for _ in pool:
            work.put(None)
        for t in pool:
            t.join(timeout=10)
        snap = srv.metrics_snapshot()
        try:
            link.send(encode_message(
                "fleet_bye", name=name, completed=snap["completed"],
                batches=snap["batches"],
                batch_fill_ratio=snap["batch_fill_ratio"],
                p95_ms=snap["latency_ms"]["p95"]))
        except OSError:
            pass
        srv.stop()
        link.close()


class _WorkerGone(Exception):
    """Raised by the coordinator-side handle when its replica process is
    unreachable — the router's cue to shed and retry a survivor."""


class _FleetWorker:
    """Coordinator-side replica handle: one socket, one receiver thread
    completing per-request slots, died-peer detection failing them."""

    def __init__(self, name: str, proc, link) -> None:
        self.name, self.proc, self.link = name, proc, link
        self.alive = True
        self.bye = None
        self._pending: dict = {}  # req_id -> [event, ok, err]
        self._lock = threading.Lock()
        self._rx = threading.Thread(target=self._recv_loop, daemon=True)
        self._rx.start()

    def outstanding(self) -> int:
        with self._lock:
            return len(self._pending)

    def submit(self, rid: str, q, deadline_ms: float):
        from raft_tpu.serve.replication import encode_message

        slot = [threading.Event(), False, None]
        with self._lock:
            if not self.alive:
                raise _WorkerGone(self.name)
            self._pending[rid] = slot
        try:
            self.link.send(encode_message("fleet_search", {"q": q},
                                          req_id=rid,
                                          deadline_ms=deadline_ms))
        except OSError:
            self._mark_dead()
            raise _WorkerGone(self.name)
        return slot

    def _recv_loop(self) -> None:
        while True:
            msg = self.link.recv(timeout=0.5)
            if msg is None:
                if self.link.closed:
                    self._mark_dead()
                    return
                continue
            if msg.kind == "fleet_reply":
                with self._lock:
                    slot = self._pending.pop(msg.static["req_id"], None)
                if slot is not None:
                    slot[1] = bool(msg.static.get("ok"))
                    slot[2] = msg.static.get("err")
                    slot[0].set()
            elif msg.kind == "fleet_bye":
                self.bye = dict(msg.static)

    def _mark_dead(self) -> None:
        with self._lock:
            self.alive = False
            slots = list(self._pending.values())
            self._pending.clear()
        for slot in slots:
            slot[1], slot[2] = False, "worker_gone"
            slot[0].set()


def _spawn_fleet(n: int, listener):
    """Launch ``n`` replica subprocesses and wait for every hello — the
    measured window starts only once the whole pod is warm."""
    procs = {}
    for i in range(n):
        env = dict(os.environ,
                   RAFT_BENCH_FLEET_PORT=str(listener.port),
                   RAFT_BENCH_FLEET_NAME=f"r{i}",
                   JAX_PLATFORMS=jax.default_backend())
        log = open(os.path.join(tempfile.gettempdir(),
                                f"raft-fleet-worker-{i}.log"), "wb")
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--fleet-worker"],
            env=env, stdout=log, stderr=subprocess.STDOUT)
        log.close()
        procs[p.pid] = p
    workers = []
    for _ in range(n):
        link = listener.accept(timeout=600.0)
        msg = link.recv(timeout=600.0)
        assert msg is not None and msg.kind == "fleet_hello", msg
        workers.append(_FleetWorker(msg.static["name"],
                                    procs[msg.static["pid"]], link))
    workers.sort(key=lambda w: w.name)
    return workers


def _shutdown_fleet(workers) -> None:
    from raft_tpu.serve.replication import encode_message

    for w in workers:
        if w.alive:
            try:
                w.link.send(encode_message("fleet_quit"))
            except OSError:
                pass
    deadline = time.monotonic() + 15.0
    for w in workers:
        while (w.alive and w.bye is None
               and time.monotonic() < deadline):
            time.sleep(0.05)
        try:
            w.proc.terminate()
        except OSError:
            pass
        w.proc.wait(timeout=15)
        w.link.close()


def _fleet_point(workers, n_clients: int, seconds: float,
                 kill_after=None) -> dict:
    """Closed loop against the pod: each client routes to the least-
    outstanding live replica, retries a failed attempt on a survivor
    while its deadline is open, and only a terminal failure with time
    still on the clock counts as dropped-in-deadline (contract: zero)."""
    stop = threading.Event()
    lock = threading.Lock()
    lat_ms: list = []
    stats = {"completed": 0, "rerouted": 0, "dropped_in_deadline": 0,
             "expired": 0}
    rid_counter = itertools.count()

    def pick():
        live = [w for w in workers if w.alive]
        return min(live, key=_FleetWorker.outstanding) if live else None

    def client(j: int) -> None:
        rng = np.random.default_rng(5000 + j)
        while not stop.is_set():
            rows = int(rng.choice(_FLEET_SHAPES))
            q = rng.standard_normal((rows, DIM)).astype(np.float32)
            t0 = time.perf_counter()
            deadline = t0 + 10 * BUDGET_MS / 1e3
            ok = False
            while True:
                now = time.perf_counter()
                if now >= deadline:
                    with lock:
                        stats["expired"] += 1
                    break
                w = pick()
                if w is None:  # whole pod dead with time on the clock
                    with lock:
                        stats["dropped_in_deadline"] += 1
                    break
                try:
                    slot = w.submit(f"{j}.{next(rid_counter)}", q,
                                    1e3 * (deadline - now))
                except _WorkerGone:
                    with lock:
                        stats["rerouted"] += 1
                    continue
                slot[0].wait(timeout=deadline - time.perf_counter() + 0.25)
                if slot[1]:
                    ok = True
                    break
                with lock:  # replica died or rejected: try a survivor
                    stats["rerouted"] += 1
            if ok:
                with lock:
                    stats["completed"] += 1
                    lat_ms.append(1e3 * (time.perf_counter() - t0))

    threads = [threading.Thread(target=client, args=(j,), daemon=True)
               for j in range(n_clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    if kill_after is not None:
        time.sleep(kill_after)
        victim = workers[0]
        victim.proc.kill()  # SIGKILL: no goodbye, the socket just dies
        victim.proc.wait(timeout=15)
        time.sleep(max(0.0, seconds - kill_after))
    else:
        time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=60)
    dt = time.perf_counter() - t0
    lat_ms.sort()
    return {
        "qps": round(stats["completed"] / dt, 1),
        "p95_ms": round(lat_ms[int(0.95 * (len(lat_ms) - 1))], 3)
        if lat_ms else None,
        **stats,
    }


def run_fleet(spec: str = REPLICAS) -> dict:
    """Replica-count sweep + SIGKILL drill; asserts the ISSUE 16
    ratchet (>=1.6x aggregate QPS at 2 replicas vs 1, p95 unchanged,
    zero dropped in-deadline requests through the kill)."""
    from raft_tpu.serve import SocketListener

    counts = tuple(int(c) for c in spec.split(","))
    points, qps_by, p95_by = [], {}, {}
    drill = None
    for n in counts:
        listener = SocketListener()
        workers = _spawn_fleet(n, listener)
        try:
            point = _fleet_point(workers, FLEET_CLIENTS * n, SECONDS)
            point = {"config": "fleet_sweep", "replicas": n,
                     "clients": FLEET_CLIENTS * n, **point}
            points.append(point)
            qps_by[n], p95_by[n] = point["qps"], point["p95_ms"]
            print(json.dumps(point), flush=True)
            if n == 2 and drill is None:
                # reuse the warm pair: kill r0 mid-load, shed to r1
                drill = _fleet_point(workers, FLEET_CLIENTS * 2,
                                     SECONDS + 2.0, kill_after=1.0)
                drill = {"config": "fleet_drill", "replicas": n,
                         "killed": workers[0].name, **drill}
                print(json.dumps(drill), flush=True)
                assert drill["dropped_in_deadline"] == 0, drill
                assert drill["expired"] == 0, drill
                assert drill["rerouted"] > 0, \
                    "kill drill never exercised the shed path"
        finally:
            _shutdown_fleet(workers)
            listener.close()
    if 1 in qps_by and 2 in qps_by:
        ratio = qps_by[2] / qps_by[1]
        assert ratio >= 1.6, f"2-replica scaling {ratio:.2f}x < 1.6x"
        assert p95_by[2] <= BUDGET_MS, p95_by
        assert p95_by[2] <= 1.5 * p95_by[1] + 2.0, \
            f"p95 moved: {p95_by[1]} -> {p95_by[2]} ms"
    top = max(qps_by)
    final = {
        "metric": "serve_fleet_qps_at_p95_budget",
        "value": qps_by[top],
        "unit": f"qps@{top}replicas,p95<={BUDGET_MS:g}ms",
        "scaling_x2": round(qps_by[2] / qps_by[1], 2)
        if 1 in qps_by and 2 in qps_by else None,
        "family": FAMILY, "rows": ROWS, "dim": DIM, "k": K,
        "ladder": list(LADDER), "fleet_wait_ms": FLEET_WAIT_MS,
        "clients_per_replica": FLEET_CLIENTS,
        "backend": jax.default_backend(),
        "points": points,
        "drill": drill,
    }
    print(json.dumps(final), flush=True)
    return final


def run(seconds: float = SECONDS, clients=CLIENTS) -> dict:
    """Build index, start server, sweep concurrency; returns the final
    result dict (also printed as the last JSON line)."""
    from raft_tpu.serve import SearchServer, ServerConfig

    rng = np.random.default_rng(0)
    db = rng.standard_normal((ROWS, DIM)).astype(np.float32)
    index, params = _build_index(db)
    cfg = ServerConfig(ladder=LADDER, max_wait_ms=1.0,
                       max_queue=max(256, 32 * max(clients)))
    srv = SearchServer(index, k=K, params=params, config=cfg)
    t0 = time.perf_counter()
    n_exec = srv.warmup()
    compile_s = time.perf_counter() - t0
    print(json.dumps({"config": "serve_warmup", "family": srv.family,
                      "executables": n_exec,
                      "compile_s": round(compile_s, 2)}), flush=True)
    srv.start(warmup=False)

    best = {"qps": 0.0, "p95_ms": None, "clients": 0}
    points = []
    try:
        for n in clients:
            qps, p95, extra = _sweep_point(srv, n, seconds, rng_seed=17 * n)
            point = {"config": "serve_sweep", "clients": n,
                     "qps": round(qps, 1), "p95_ms": p95, **extra}
            points.append(point)
            print(json.dumps(point), flush=True)
            if p95 <= BUDGET_MS and qps > best["qps"]:
                best = {"qps": qps, "p95_ms": p95, "clients": n}
        swap = None
        if SWAPS:
            swap = _swap_phase(srv, db, best["clients"] or max(clients),
                               SWAPS, seconds)
            print(json.dumps({"config": "serve_swap_phase", **swap}),
                  flush=True)
    finally:
        srv.stop()

    snap = srv.metrics_snapshot()
    final = {
        "metric": "serve_qps_at_p95_budget",
        "value": round(best["qps"], 1),
        "unit": f"qps@p95<={BUDGET_MS:g}ms",
        "clients": best["clients"],
        "p95_ms": best["p95_ms"],
        "family": srv.family,
        "rows": ROWS, "dim": DIM, "k": K, "ladder": list(srv.ladder),
        "backend": jax.default_backend(),
        "points": points,
        "serving_metrics": snap,
    }
    if SWAPS:
        final["swap"] = swap
    print(json.dumps(final), flush=True)
    return final


if __name__ == "__main__":
    if "--fleet-worker" in sys.argv:
        run_fleet_worker()
    elif REPLICAS:
        run_fleet()
    elif RECOVERY:
        run_recovery()
    elif FAILOVER:
        run_failover()
    else:
        run()
