"""Micro-benchmark suites — parity with ``cpp/bench/prims``
(``cpp/bench/prims/CMakeLists.txt:70-97``: select_k, reduce, norm, gather,
rng, make_blobs, sparse conversions, sddmm, masked_matmul, popc, bitset;
fixture ``common/benchmark.hpp:99,344``).

Usage:  python bench/prims.py [suite ...] [--quick] [--no-record]

Prints one JSON line per case: {"suite", "case", "ms", "items_per_s"}.
Times are min-of-3 with host-fetch barriers (the only reliable sync on the
remote-TPU tunnel — see bench.py).

**Ratchet**: results are recorded in ``bench/PRIMS_HISTORY.json`` (committed
each round; per-case best ms per backend).  A case ≥ 1.3× slower than its
recorded best prints a loud ``REGRESSION`` line to stderr and the process
exits nonzero — the per-primitive analog of bench.py's headline ratchet
(the reference treats micro-bench as first-class; VERDICT r2 next #5).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# persistent XLA executable cache (shared with bench.py): repeat runs
# on the same machine skip recompilation
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax

from _platform import pin_backend

# MUST precede any backend use (axon sitecustomize overrides the env var)
pin_backend(sys.argv)

import jax.numpy as jnp
import numpy as np

from _timing import timeit as _time


HISTORY = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "PRIMS_HISTORY.json")
REGRESSION_RATIO = 1.3
_results: list = []


def report(suite, case, seconds, items):
    print(json.dumps({"suite": suite, "case": case,
                      "ms": round(seconds * 1e3, 3),
                      "items_per_s": round(items / seconds, 1)}))
    _results.append((f"{suite}/{case}", seconds * 1e3))


def ratchet(record: bool, ran_suites) -> int:
    """Compare this run against the per-backend best and update the file.
    Returns the number of regressions: cases ≥ REGRESSION_RATIO × best,
    **plus recorded cases of a suite that ran but produced no result this
    time** — a primitive that regresses into crashing must not pass the
    gate silently."""
    try:
        with open(HISTORY) as f:
            hist = json.load(f)
    except (OSError, ValueError):
        hist = {}
    backend = jax.default_backend()
    best = hist.setdefault(backend, {})
    # provenance stamp (VERDICT r3 next #8): no artifact may be mistaken
    # for TPU evidence when it is a CPU stand-in
    import datetime

    hist.setdefault("_meta", {})[backend] = {
        "backend": backend, "date": datetime.date.today().isoformat(),
        "cases": len(_results)}
    regressions = 0
    seen = set()
    for key, ms in _results:
        seen.add(key)
        prev = best.get(key)
        if prev is not None and ms > prev * REGRESSION_RATIO:
            regressions += 1
            print(f"REGRESSION {key}: {ms:.3f} ms vs best {prev:.3f} ms "
                  f"({ms / prev:.2f}x)", file=sys.stderr)
        if prev is None or ms < prev:
            best[key] = round(ms, 3)
    stale = []
    for key in best:
        suite = key.split("/", 1)[0]
        if suite in ran_suites and key not in seen:
            regressions += 1
            stale.append(key)
            print(f"REGRESSION {key}: recorded case produced no result "
                  f"(crashed or dropped)", file=sys.stderr)
    if record:
        # self-heal: after failing THIS run loudly, drop the stale keys so
        # a deliberate workload change (e.g. per-backend case narrowing)
        # doesn't wedge every future run on the same complaint
        for key in stale:
            del best[key]
        with open(HISTORY, "w") as f:
            json.dump(hist, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"ratchet: {len(_results)} cases vs {HISTORY} "
              f"[{backend}], {regressions} regression(s)", file=sys.stderr)
    return regressions


def bench_select_k(quick):
    from raft_tpu.matrix import SelectAlgo, select_k

    # Off-TPU: the Pallas kernel would run in interpret mode (numbers are
    # noise) and the big shapes exhaust host memory — bench the quick
    # shape with the XLA algos only.  History is per-backend, so the
    # lighter CPU workload never mixes with TPU bests.
    on_tpu = jax.default_backend() == "tpu"
    shapes = [(1024, 16384, 32)] if (quick or not on_tpu) else [
        (1024, 16384, 32), (4096, 65536, 10), (16384, 8192, 64)]
    algos = (SelectAlgo.kTopK, SelectAlgo.kPartialBitonic,
             SelectAlgo.kBinSelect) if on_tpu else (
        SelectAlgo.kTopK, SelectAlgo.kBinSelect)
    key = jax.random.PRNGKey(0)
    for rows, cols, k in shapes:
        x = jax.block_until_ready(jax.random.normal(key, (rows, cols), jnp.float32))
        for algo in algos:
            if algo is SelectAlgo.kPartialBitonic and k > 64:
                continue
            try:
                t = _time(lambda a=algo: select_k(x, k, algo=a))
            except Exception:
                continue
            report("select_k", f"{rows}x{cols}_k{k}_{algo.name}", t, rows)


def bench_reduce(quick):
    from raft_tpu.linalg import reduce as lreduce
    from raft_tpu.linalg.reduce import Apply

    shapes = [(4096, 4096)] if quick else [(4096, 4096), (32768, 1024), (256, 262144)]
    key = jax.random.PRNGKey(1)
    for r, c in shapes:
        x = jax.block_until_ready(jax.random.normal(key, (r, c), jnp.float32))
        t = _time(lambda: lreduce(x, apply=Apply.ALONG_ROWS))
        report("reduce", f"{r}x{c}_rows", t, r * c)


def bench_norm(quick):
    from raft_tpu.linalg import row_norm

    key = jax.random.PRNGKey(2)
    x = jax.block_until_ready(jax.random.normal(key, (16384, 512), jnp.float32))
    t = _time(lambda: row_norm(x, norm_type="l2"))
    report("norm", "16384x512_l2", t, x.size)


def bench_normalize(quick):
    from raft_tpu.linalg import normalize

    key = jax.random.PRNGKey(7)
    x = jax.block_until_ready(jax.random.normal(key, (16384, 512), jnp.float32))
    t = _time(lambda: normalize(x))
    report("normalize", "16384x512_l2", t, x.size)


def bench_argmin(quick):
    from raft_tpu.matrix import argmin

    key = jax.random.PRNGKey(8)
    x = jax.block_until_ready(jax.random.normal(key, (8192, 4096), jnp.float32))
    t = _time(lambda: argmin(x))
    report("argmin", "8192x4096_rows", t, x.size)


def bench_copy(quick):
    """The mdspan-copy role (``bench/prims`` has a copy suite): host→device
    ingest of an F-order array and device→host F-order export."""
    from raft_tpu.core.copy import copy

    h = np.asfortranarray(np.random.default_rng(9).standard_normal(
        (4096, 1024)).astype(np.float32))
    t = _time(lambda: copy(h, memory="device"))
    report("copy", "F_host_to_device_4096x1024", t, h.size)
    d = copy(np.ascontiguousarray(h), memory="device")
    t = _time(lambda: copy(d, memory="host", layout="F"))
    report("copy", "device_to_F_host_4096x1024", t, h.size)


def bench_gather(quick):
    from raft_tpu.matrix import gather

    key = jax.random.PRNGKey(3)
    x = jax.block_until_ready(jax.random.normal(key, (1 << 20, 64), jnp.float32))
    idx = jax.block_until_ready(
        jax.random.randint(key, (1 << 16,), 0, 1 << 20, jnp.int32))
    t = _time(lambda: gather(x, idx))
    report("gather", "1Mx64_take64k", t, int(idx.size))


def bench_rng(quick):
    from raft_tpu.random import RngState, normal, uniform

    n = 1 << 22 if quick else 1 << 24
    st = RngState(0)
    t = _time(lambda: uniform(st, (n,)))
    report("rng", f"uniform_{n}", t, n)
    t = _time(lambda: normal(st, (n,)))
    report("rng", f"normal_{n}", t, n)


def bench_make_blobs(quick):
    from raft_tpu.random import RngState, make_blobs

    n = 1 << 18
    t = _time(lambda: make_blobs(RngState(0), n, 64, n_clusters=64))
    report("make_blobs", f"{n}x64_c64", t, n)


def bench_sparse_convert(quick):
    from raft_tpu.sparse import dense_to_csr, csr_to_dense

    key = jax.random.PRNGKey(4)
    dense = jax.random.normal(key, (2048, 2048), jnp.float32)
    dense = jax.block_until_ready(
        jnp.where(jax.random.uniform(key, dense.shape) < 0.05, dense, 0.0))
    t = _time(lambda: dense_to_csr(dense))
    report("sparse_convert", "dense_to_csr_2048^2_5pct", t, dense.size)
    csr = dense_to_csr(dense)
    t = _time(lambda: csr_to_dense(csr))
    report("sparse_convert", "csr_to_dense_2048^2_5pct", t, dense.size)


def bench_sddmm(quick):
    from raft_tpu.sparse import dense_to_csr, sddmm

    key = jax.random.PRNGKey(5)
    a = jax.block_until_ready(jax.random.normal(key, (2048, 256), jnp.float32))
    b = jax.block_until_ready(jax.random.normal(key, (256, 2048), jnp.float32))
    mask = jnp.where(jax.random.uniform(key, (2048, 2048)) < 0.02, 1.0, 0.0)
    s = dense_to_csr(jax.block_until_ready(mask))
    t = _time(lambda: sddmm(a, b, s).data)
    report("sddmm", "2048^2_2pct_k256", t, int(s.nnz))


def bench_masked_matmul(quick):
    from raft_tpu.sparse import dense_to_csr, masked_matmul

    key = jax.random.PRNGKey(6)
    a = jax.block_until_ready(jax.random.normal(key, (2048, 256), jnp.float32))
    b = jax.block_until_ready(jax.random.normal(key, (2048, 256), jnp.float32))
    mask = jnp.where(jax.random.uniform(key, (2048, 2048)) < 0.02, 1.0, 0.0)
    s = dense_to_csr(jax.block_until_ready(mask))
    t = _time(lambda: masked_matmul(a, b, s).data)
    report("masked_matmul", "2048^2_2pct_k256", t, int(s.nnz))


def bench_bitset(quick):
    from raft_tpu.core.bitset import Bitset, popc

    n = 1 << 24
    key = jax.random.PRNGKey(7)
    idx = jax.block_until_ready(
        jax.random.randint(key, (1 << 18,), 0, n, jnp.int32))
    bs = Bitset.zeros(n) if hasattr(Bitset, "zeros") else Bitset(
        jnp.zeros(((n + 31) // 32,), jnp.uint32), n)
    t = _time(lambda: bs.set(idx).words)
    report("bitset", f"set_{1 << 18}_of_{n}", t, 1 << 18)
    bs2 = bs.set(idx)
    t = _time(lambda: popc(bs2.words))
    report("bitset", f"popc_{n}", t, n)


def bench_ivf_pq_tiers(quick):
    """LUT vs recon search-tier crossover + 4-bit packed-code cost (VERDICT
    r3 weak #7: the 'half the gather traffic' claim of ``with_packed_codes``
    and the LUT/recon tier choice had no measurement anywhere).  One small
    clustered corpus, three indexes sharing the coarse quantizer config:

    * ``search_recon`` — bf16 reconstruction-slab tier (HBM-heavy, MXU-fast)
    * ``search_lut``   — uint8 code-resident ADC tier
    * ``search_lut_packed`` — 4-bit codes, two per byte (half the gather)
    """
    from raft_tpu.neighbors import ivf_pq

    n, d = (20_000, 32) if quick else (200_000, 64)
    nq, k = 256, 10
    n_lists = 64 if quick else 512
    key = jax.random.PRNGKey(3)
    kc, kp = jax.random.split(key)
    centers = jax.random.normal(kc, (64, d), jnp.float32) * 3.0
    cid = jax.random.randint(kp, (n + nq,), 0, 64)
    pts = centers[cid] + jax.random.normal(kp, (n + nq, d), jnp.float32)
    x = jax.block_until_ready(pts[:n])
    q = jax.block_until_ready(pts[n:])

    sp = ivf_pq.IvfPqSearchParams(n_probes=8)
    idx8 = ivf_pq.build(x, ivf_pq.IvfPqIndexParams(
        n_lists=n_lists, pq_dim=d // 2, seed=0))
    t = _time(lambda: ivf_pq.search(
        idx8, q, k, ivf_pq.IvfPqSearchParams(n_probes=8, mode="recon")))
    report("ivf_pq_tiers", f"search_recon_{n}x{d}", t, nq)
    idx_lut = idx8.without_recon()
    t = _time(lambda: ivf_pq.search(
        idx_lut, q, k, ivf_pq.IvfPqSearchParams(n_probes=8, mode="lut")))
    report("ivf_pq_tiers", f"search_lut_{n}x{d}", t, nq)

    idx4 = ivf_pq.build(x, ivf_pq.IvfPqIndexParams(
        n_lists=n_lists, pq_dim=d // 2, pq_bits=4, seed=0)).without_recon()
    t = _time(lambda: ivf_pq.search(idx4, q, k, sp))
    report("ivf_pq_tiers", f"search_lut4_{n}x{d}", t, nq)
    idx4p = idx4.with_packed_codes()
    t = _time(lambda: ivf_pq.search(idx4p, q, k, sp))
    report("ivf_pq_tiers", f"search_lut4_packed_{n}x{d}", t, nq)


def bench_ivf_flat_tiers(quick):
    """Integer-corpus scoring tier: ivf_flat search on a uint8 corpus takes
    one exact bf16 MXU pass per probe block vs the f32 corpus's bf16x6
    HIGHEST passes (`neighbors/_packing.py:exact_gathered_dots`) — measures
    what the tier buys on real hardware."""
    from raft_tpu.neighbors import ivf_flat

    n, d = (20_000, 32) if quick else (200_000, 64)
    nq, k = 256, 10
    n_lists = 64 if quick else 512
    key = jax.random.PRNGKey(9)
    xu8 = jax.block_until_ready(
        jax.random.randint(key, (n, d), 0, 256, jnp.int32).astype(jnp.uint8))
    qu8 = jax.block_until_ready(
        jax.random.randint(jax.random.fold_in(key, 1), (nq, d), 0, 256,
                           jnp.int32).astype(jnp.uint8))
    sp = ivf_flat.IvfFlatSearchParams(n_probes=8)
    idx_u8 = ivf_flat.build(xu8, ivf_flat.IvfFlatIndexParams(n_lists=n_lists,
                                                             seed=0))
    t = _time(lambda: ivf_flat.search(idx_u8, qu8, k, sp))
    report("ivf_flat_tiers", f"search_uint8_{n}x{d}", t, nq)
    idx_f = ivf_flat.build(xu8.astype(jnp.float32),
                           ivf_flat.IvfFlatIndexParams(n_lists=n_lists,
                                                       seed=0))
    qf = qu8.astype(jnp.float32)
    t = _time(lambda: ivf_flat.search(idx_f, qf, k, sp))
    report("ivf_flat_tiers", f"search_f32_{n}x{d}", t, nq)


SUITES = {
    "select_k": bench_select_k,
    "ivf_pq_tiers": bench_ivf_pq_tiers,
    "ivf_flat_tiers": bench_ivf_flat_tiers,
    "reduce": bench_reduce,
    "norm": bench_norm,
    "normalize": bench_normalize,
    "argmin": bench_argmin,
    "copy": bench_copy,
    "gather": bench_gather,
    "rng": bench_rng,
    "make_blobs": bench_make_blobs,
    "sparse_convert": bench_sparse_convert,
    "sddmm": bench_sddmm,
    "masked_matmul": bench_masked_matmul,
    "bitset": bench_bitset,
}


def main() -> int:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    quick = "--quick" in sys.argv
    names = args or list(SUITES)
    ran = set()
    for name in names:
        fn = SUITES.get(name)
        if fn is None:
            print(f"unknown suite {name!r}; have {sorted(SUITES)}", file=sys.stderr)
            continue
        ran.add(name)
        try:
            fn(quick)
        except Exception as e:  # noqa: BLE001 — keep the harness going
            print(json.dumps({"suite": name, "error": f"{type(e).__name__}: {e}"}))
    # record only full default runs — partial/--quick runs use lighter
    # workloads and would poison the committed bests (still compared)
    record = not args and not quick and "--no-record" not in sys.argv
    return ratchet(record, ran)


if __name__ == "__main__":
    sys.exit(1 if main() else 0)
