"""Telemetry overhead microbench — spans ON vs OFF on the serve hot path.

Drives one step-mode :class:`raft_tpu.serve.SearchServer` through a
fixed request count twice: once with the flight recorder enabled (the
shipping default) and once with it disabled, plus a raw
``SpanRecorder`` op-cost table (span / event / post-hoc record).  The
headline metric is the **per-request telemetry cost in microseconds**
and its fraction of the request's own latency — the "low-overhead"
claim of ISSUE 9 as a number that gets re-measured every round instead
of asserted in prose.

The bound asserted here (and pinned by the committed
``bench/OBS_OVERHEAD_CPU.json``) is deliberately loose — CI boxes
jitter — but catches the failure class that matters: a lock or an
allocation landing on the per-record path turns ~µs into ~ms and trips
it immediately.

Prints one JSON line per phase and ONE final JSON line in the
``bench.py`` driver format.

A third arm measures the **quality sampler** (ISSUE 11): the same loop
with `attach_quality(sample_fraction=...)` — the hot-path cost is one
hash per request plus an array copy + queue put for the sampled
fraction, with the oracle scoring on a daemon worker.  Its budget is separate
(`bench/QUALITY_OVERHEAD_CPU.json` pins it); the true cost is tens of
microseconds, but the bound stays at the box's noise floor.

Scale knobs (CPU smoke -> TPU record):
  RAFT_BENCH_OBS_ROWS      index rows           (default 20_000)
  RAFT_BENCH_OBS_DIM       vector dim           (default 64)
  RAFT_BENCH_OBS_REQUESTS  requests per phase   (default 400)
  RAFT_BENCH_OBS_MAX_FRAC  overhead budget as a fraction of the
                           spans-off request latency (default 0.05)
  RAFT_BENCH_OBS_SAMPLE_FRAC        quality sampler fraction (default 0.01)
  RAFT_BENCH_OBS_SAMPLER_MAX_FRAC   sampler overhead budget as a fraction
                                    of the sampler-off latency (default 0.05)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax  # noqa: E402

from _platform import pin_backend  # noqa: E402

pin_backend(sys.argv)

import numpy as np  # noqa: E402

from raft_tpu.obs import SpanRecorder  # noqa: E402
from raft_tpu.serve import SearchServer, ServerConfig  # noqa: E402

ROWS = int(os.environ.get("RAFT_BENCH_OBS_ROWS", "20000"))
DIM = int(os.environ.get("RAFT_BENCH_OBS_DIM", "64"))
REQUESTS = int(os.environ.get("RAFT_BENCH_OBS_REQUESTS", "400"))
MAX_FRAC = float(os.environ.get("RAFT_BENCH_OBS_MAX_FRAC", "0.05"))
SAMPLE_FRAC = float(os.environ.get("RAFT_BENCH_OBS_SAMPLE_FRAC", "0.01"))
SAMPLER_MAX_FRAC = float(
    os.environ.get("RAFT_BENCH_OBS_SAMPLER_MAX_FRAC", "0.05"))


def _drive(recorder: SpanRecorder, queries, db,
           sample_fraction: float = 0.0) -> dict:
    """Step-driven closed loop: one request per step, fixed bucket."""
    srv = SearchServer(db, k=10, config=ServerConfig(ladder=(8,)),
                       recorder=recorder)
    est = None
    if sample_fraction > 0:
        from raft_tpu.obs import QualityConfig

        est = srv.attach_quality(QualityConfig(
            sample_fraction=sample_fraction, rows_cap=8))
        est.oracle_ids(queries[0])  # oracle jit outside the timed window
        est.start()
    srv.warmup()
    for j in range(8):  # absorb first-dispatch costs outside the window
        fut = srv.submit(queries[j % len(queries)])
        srv.step()
        fut.result(timeout=30)
    t0 = time.perf_counter()
    for j in range(REQUESTS):
        fut = srv.submit(queries[j % len(queries)])
        srv.step()
        fut.result(timeout=30)
    dt = time.perf_counter() - t0
    snap = srv.metrics.snapshot()
    out = {"wall_s": round(dt, 4),
           "us_per_request": round(dt / REQUESTS * 1e6, 2),
           "p50_ms": snap["latency_ms"]["p50"],
           "completed": snap["completed"],
           "spans_recorded": recorder.stats()["recorded"]}
    if est is not None:
        est.stop()
        est.drain()                 # score any stragglers for the census
        out.update({"quality_samples": snap["quality_samples"],
                    "quality_sample_drops": snap["quality_sample_drops"],
                    "quality_scored": est.samples_total})
    return out


def _op_costs() -> dict:
    """Raw recorder op cost (ns/op) with no server in the way."""
    rec = SpanRecorder(4096)
    reps = 20_000
    out = {}

    def best_of(fn, n=3):
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter_ns()
            fn()
            best = min(best, (time.perf_counter_ns() - t0) / reps)
        return round(best, 1)

    def spans():
        for _ in range(reps):
            with rec.span("bench.span", bucket=8):
                pass

    def events():
        for _ in range(reps):
            rec.event("bench.event", reason="x")

    def records():
        for _ in range(reps):
            rec.record("bench.record", 1, 2, part=0)

    out["span_ns"] = best_of(spans)
    out["event_ns"] = best_of(events)
    out["record_ns"] = best_of(records)
    rec.enabled = False
    out["disabled_span_ns"] = best_of(spans)
    return out


def main() -> int:
    rng = np.random.default_rng(7)
    db = rng.standard_normal((ROWS, DIM)).astype(np.float32)
    queries = [rng.standard_normal((4, DIM)).astype(np.float32)
               for _ in range(16)]

    ops = _op_costs()
    print(json.dumps({"config": "obs_op_costs", **ops}), flush=True)

    # Single-run wall-clock deltas on a shared box swing several percent
    # either way — more than either effect being measured — so the three
    # arms run alternately and compare min-of-N: the minimum is the run
    # with the least scheduler interference on each side.  The sampler
    # arm's baseline is the spans-on loop (the shipping default is spans
    # on, and the sampler rides on top).
    on_runs, off_runs, sampler_runs = [], [], []
    for _ in range(3):
        sampler_runs.append(_drive(SpanRecorder(4096), queries, db,
                                   sample_fraction=SAMPLE_FRAC))
        on_runs.append(_drive(SpanRecorder(4096), queries, db))
        off_runs.append(_drive(SpanRecorder(4096, enabled=False),
                               queries, db))
    on, off, sampler = on_runs[0], off_runs[0], sampler_runs[0]
    print(json.dumps({"config": "spans_on", **on}), flush=True)
    print(json.dumps({"config": "spans_off", **off}), flush=True)
    print(json.dumps({"config": "sampler_on", **sampler}), flush=True)

    off_us = min(r["us_per_request"] for r in off_runs)
    base_us = min(r["us_per_request"] for r in on_runs)
    sampler_best_us = min(r["us_per_request"] for r in sampler_runs)
    overhead_us = base_us - off_us
    frac = overhead_us / off_us
    sampler_us = sampler_best_us - base_us
    sampler_frac = sampler_us / base_us
    final = {
        "metric": "obs_overhead_us_per_request",
        "value": round(overhead_us, 2),
        "unit": f"us@{REQUESTS}req",
        "fraction_of_request": round(frac, 4),
        "budget_fraction": MAX_FRAC,
        "sampler_fraction": SAMPLE_FRAC,
        "sampler_overhead_us": round(sampler_us, 2),
        "sampler_fraction_of_request": round(sampler_frac, 4),
        "sampler_budget_fraction": SAMPLER_MAX_FRAC,
        "backend": jax.default_backend(),
        "rows": ROWS, "dim": DIM, "requests": REQUESTS,
        "op_costs_ns": ops,
        "points": [{"config": "spans_on", **on},
                   {"config": "spans_off", **off},
                   {"config": "sampler_on", **sampler}],
    }
    print(json.dumps(final, indent=2 if sys.stdout.isatty() else None),
          flush=True)
    # the bound: telemetry must stay a rounding error on the request.
    # A negative overhead just means the delta drowned in scheduler noise.
    assert frac <= MAX_FRAC, (
        f"telemetry overhead {overhead_us:.1f}us/request is "
        f"{frac:.1%} of the spans-off request ({off['us_per_request']}us) "
        f"— budget {MAX_FRAC:.0%}")
    assert sampler_frac <= SAMPLER_MAX_FRAC, (
        f"quality sampler at {SAMPLE_FRAC:.0%} adds "
        f"{sampler_us:.1f}us/request = {sampler_frac:.1%} of the "
        f"sampler-off request (min-of-{len(on_runs)} {base_us}us) "
        f"— budget {SAMPLER_MAX_FRAC:.0%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
