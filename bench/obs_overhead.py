"""Telemetry overhead microbench — spans ON vs OFF on the serve hot path.

Drives one step-mode :class:`raft_tpu.serve.SearchServer` through a
fixed request count twice: once with the flight recorder enabled (the
shipping default) and once with it disabled, plus a raw
``SpanRecorder`` op-cost table (span / event / post-hoc record).  The
headline metric is the **per-request telemetry cost in microseconds**
and its fraction of the request's own latency — the "low-overhead"
claim of ISSUE 9 as a number that gets re-measured every round instead
of asserted in prose.

The bound asserted here (and pinned by the committed
``bench/OBS_OVERHEAD_CPU.json``) is deliberately loose — CI boxes
jitter — but catches the failure class that matters: a lock or an
allocation landing on the per-record path turns ~µs into ~ms and trips
it immediately.

Prints one JSON line per phase and ONE final JSON line in the
``bench.py`` driver format.

Scale knobs (CPU smoke -> TPU record):
  RAFT_BENCH_OBS_ROWS      index rows           (default 20_000)
  RAFT_BENCH_OBS_DIM       vector dim           (default 64)
  RAFT_BENCH_OBS_REQUESTS  requests per phase   (default 400)
  RAFT_BENCH_OBS_MAX_FRAC  overhead budget as a fraction of the
                           spans-off request latency (default 0.05)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax  # noqa: E402

from _platform import pin_backend  # noqa: E402

pin_backend(sys.argv)

import numpy as np  # noqa: E402

from raft_tpu.obs import SpanRecorder  # noqa: E402
from raft_tpu.serve import SearchServer, ServerConfig  # noqa: E402

ROWS = int(os.environ.get("RAFT_BENCH_OBS_ROWS", "20000"))
DIM = int(os.environ.get("RAFT_BENCH_OBS_DIM", "64"))
REQUESTS = int(os.environ.get("RAFT_BENCH_OBS_REQUESTS", "400"))
MAX_FRAC = float(os.environ.get("RAFT_BENCH_OBS_MAX_FRAC", "0.05"))


def _drive(recorder: SpanRecorder, queries, db) -> dict:
    """Step-driven closed loop: one request per step, fixed bucket."""
    srv = SearchServer(db, k=10, config=ServerConfig(ladder=(8,)),
                       recorder=recorder)
    srv.warmup()
    for j in range(8):  # absorb first-dispatch costs outside the window
        fut = srv.submit(queries[j % len(queries)])
        srv.step()
        fut.result(timeout=30)
    t0 = time.perf_counter()
    for j in range(REQUESTS):
        fut = srv.submit(queries[j % len(queries)])
        srv.step()
        fut.result(timeout=30)
    dt = time.perf_counter() - t0
    snap = srv.metrics.snapshot()
    return {"wall_s": round(dt, 4),
            "us_per_request": round(dt / REQUESTS * 1e6, 2),
            "p50_ms": snap["latency_ms"]["p50"],
            "completed": snap["completed"],
            "spans_recorded": recorder.stats()["recorded"]}


def _op_costs() -> dict:
    """Raw recorder op cost (ns/op) with no server in the way."""
    rec = SpanRecorder(4096)
    reps = 20_000
    out = {}

    def best_of(fn, n=3):
        best = float("inf")
        for _ in range(n):
            t0 = time.perf_counter_ns()
            fn()
            best = min(best, (time.perf_counter_ns() - t0) / reps)
        return round(best, 1)

    def spans():
        for _ in range(reps):
            with rec.span("bench.span", bucket=8):
                pass

    def events():
        for _ in range(reps):
            rec.event("bench.event", reason="x")

    def records():
        for _ in range(reps):
            rec.record("bench.record", 1, 2, part=0)

    out["span_ns"] = best_of(spans)
    out["event_ns"] = best_of(events)
    out["record_ns"] = best_of(records)
    rec.enabled = False
    out["disabled_span_ns"] = best_of(spans)
    return out


def main() -> int:
    rng = np.random.default_rng(7)
    db = rng.standard_normal((ROWS, DIM)).astype(np.float32)
    queries = [rng.standard_normal((4, DIM)).astype(np.float32)
               for _ in range(16)]

    ops = _op_costs()
    print(json.dumps({"config": "obs_op_costs", **ops}), flush=True)

    on = _drive(SpanRecorder(4096), queries, db)
    off = _drive(SpanRecorder(4096, enabled=False), queries, db)
    print(json.dumps({"config": "spans_on", **on}), flush=True)
    print(json.dumps({"config": "spans_off", **off}), flush=True)

    overhead_us = on["us_per_request"] - off["us_per_request"]
    frac = overhead_us / off["us_per_request"]
    final = {
        "metric": "obs_overhead_us_per_request",
        "value": round(overhead_us, 2),
        "unit": f"us@{REQUESTS}req",
        "fraction_of_request": round(frac, 4),
        "budget_fraction": MAX_FRAC,
        "backend": jax.default_backend(),
        "rows": ROWS, "dim": DIM, "requests": REQUESTS,
        "op_costs_ns": ops,
        "points": [{"config": "spans_on", **on},
                   {"config": "spans_off", **off}],
    }
    print(json.dumps(final, indent=2 if sys.stdout.isatty() else None),
          flush=True)
    # the bound: telemetry must stay a rounding error on the request.
    # A negative overhead just means the delta drowned in scheduler noise.
    assert frac <= MAX_FRAC, (
        f"telemetry overhead {overhead_us:.1f}us/request is "
        f"{frac:.1%} of the spans-off request ({off['us_per_request']}us) "
        f"— budget {MAX_FRAC:.0%}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
