"""Flagship-kernel profiling — where does brute-force kNN time go?

Splits the wall-clock QPS into its parts (VERDICT r2 weak #1):

* **tunnel RTT**: single-dispatch latency minus pipelined per-call time
  (depth-8 pipelining keeps the device queue full, amortizing the remote
  link round trip),
* **MXU floor**: a *tiled* bf16 matmul of the same shape with a min
  epilogue per tile (the (m, n) product is never materialized — at
  10k×1M f32 it would be 40 GB, over any chip's HBM) — the physically
  unbeatable time for the distance pass,
* **fused_shortlist** alone across a (bm, bn) block-size grid,
* the post-shortlist stages one at a time: the (m, 2·bn)→cand top-k
  cut (exact ``lax.top_k`` vs ``approx_max_k``), the (m, cand) row
  gather + exact f32 re-score,
* **full fast path** and the exact path, for contrast.

Usage: ``python bench/profile_knn.py [--m 10000 --n 1000000 --d 128]``.
Prints one JSON line per measurement; effective TFLOP/s uses
``2·m·n·d / t``.
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# persistent XLA executable cache (shared with bench.py): repeat runs
# on the same machine skip recompilation
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax

from _platform import pin_backend

# MUST precede any backend use (axon sitecustomize overrides the env var)
pin_backend(sys.argv)

import jax.numpy as jnp


def _arg(name, default):
    if name in sys.argv:
        return int(sys.argv[sys.argv.index(name) + 1])
    return default


# one timing protocol for every bench file (see ann.fetch docstring)
from ann import fetch, measure_qps, single_latency


def pipelined(fn, depth: int = 8) -> float:
    """Per-call seconds with the device queue kept full."""
    return 1.0 / measure_qps(fn, 1, reps=depth)


def single(fn, reps: int = 3) -> float:
    return single_latency(fn, reps)


@functools.partial(jax.jit, static_argnames=("tile",))
def _tiled_min_matmul(x, y, tile: int = 65536):
    """min_j(x·yᵀ) without materializing (m, n): scan over column tiles."""
    n, d = y.shape
    pad = (-n) % tile
    if pad:
        y = jnp.concatenate([y, jnp.zeros((pad, d), y.dtype)], axis=0)
    ytiles = y.reshape(-1, tile, d)

    def step(best, yt):
        dots = jnp.dot(x, yt.T, preferred_element_type=jnp.float32)
        return jnp.minimum(best, jnp.min(dots, axis=1)), None

    init = jnp.full((x.shape[0],), jnp.inf, jnp.float32)
    best, _ = jax.lax.scan(step, init, ytiles)
    return best


def main() -> None:
    m = _arg("--m", 10_000)
    n = _arg("--n", 1_000_000)
    d = _arg("--d", 128)
    k = 10
    cand = 64
    flops = 2.0 * m * n * d

    key = jax.random.PRNGKey(0)
    kq, kd = jax.random.split(key)
    db = jax.block_until_ready(jax.random.normal(kd, (n, d), jnp.float32))
    q = jax.block_until_ready(jax.random.normal(kq, (m, d), jnp.float32))
    dbb = jax.block_until_ready(db.astype(jnp.bfloat16))
    qb = jax.block_until_ready(q.astype(jnp.bfloat16))
    yn = jax.block_until_ready(jnp.sum(db.astype(jnp.float32) ** 2, axis=1))

    def emit(case, t, extra=None):
        print(json.dumps({
            "case": case, "ms": round(t * 1e3, 2),
            "tflops": round(flops / t / 1e12, 1),
            **(extra or {})}), flush=True)

    def guarded(case, fn, **kw):
        try:
            t = pipelined(fn)
        except Exception as e:  # noqa: BLE001 — one OOM must not kill the study
            print(json.dumps({"case": case, "error": str(e)[:160]}), flush=True)
            return
        emit(case, t, kw or None)

    guarded("matmul_floor_bf16_tiled", lambda: _tiled_min_matmul(qb, dbb))

    # fused_shortlist block-size sweep
    from raft_tpu.ops.pallas.fused_l2_topk import fused_shortlist

    for bm in (256, 512, 1024):
        for bn in (1024, 2048):
            guarded(f"shortlist_bm{bm}_bn{bn}",
                    lambda bm=bm, bn=bn: fused_shortlist(qb, dbb, yn, bm=bm, bn=bn))

    # post-shortlist stages, isolated on a held shortlist output
    sv, si = fetch(fused_shortlist(qb, dbb, yn, bm=1024, bn=1024))
    sv = jax.block_until_ready(sv)
    si = jax.block_until_ready(si)

    cut_exact = jax.jit(lambda v: jax.lax.top_k(-v, cand))
    guarded("cut_topk_exact_2048to64", lambda: cut_exact(sv))
    cut_approx = jax.jit(lambda v: jax.lax.approx_max_k(
        -v, cand, recall_target=0.99))
    guarded("cut_topk_approx_2048to64", lambda: cut_approx(sv))

    neg, pos = fetch(cut_exact(sv))
    short = jax.block_until_ready(jnp.take_along_axis(si, pos, axis=1))

    @jax.jit
    def rescore(short):
        from raft_tpu.neighbors.brute_force import _exact_candidate_distances

        dc = _exact_candidate_distances(q, db[short], "sqeuclidean")
        negv, p2 = jax.lax.top_k(-dc, k)
        return -negv, jnp.take_along_axis(short, p2, axis=1)

    guarded("refine_gather_rescore_64", lambda: rescore(short))

    @jax.jit
    def rescore_high(short):
        # decision-tree branch 1: HIGHEST→HIGH (bf16x6 → bf16x3) on the
        # refine einsum — measures what the first tuning step would buy
        from raft_tpu.neighbors.brute_force import _exact_candidate_distances

        dc = _exact_candidate_distances(q, db[short], "sqeuclidean",
                                        precision=jax.lax.Precision.HIGH)
        negv, p2 = jax.lax.top_k(-dc, k)
        return -negv, jnp.take_along_axis(short, p2, axis=1)

    guarded("refine_gather_rescore_64_high", lambda: rescore_high(short))

    # full fast path (current defaults) + RTT split
    from raft_tpu.neighbors.brute_force import _fast_knn_impl, _knn_impl

    fast = lambda: _fast_knn_impl(q, db, k, "sqeuclidean", cand, 1024, 1024)
    t1 = single(fast)
    tp = pipelined(fast)
    emit("fast_full", tp, {
        "single_dispatch_ms": round(t1 * 1e3, 2),
        "tunnel_overhead_ms": round((t1 - tp) * 1e3, 2),
        "qps_pipelined": round(m / tp, 0)})

    guarded("exact_full", lambda: _knn_impl(q, db, k, "sqeuclidean", 65536))


if __name__ == "__main__":
    main()
