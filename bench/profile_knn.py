"""Flagship-kernel profiling — where does brute-force kNN time go?

Splits the wall-clock QPS into its parts (VERDICT r2 weak #1):

* **tunnel RTT**: single-dispatch latency minus pipelined per-call time
  (depth-8 pipelining keeps the device queue full, amortizing the remote
  link round trip),
* **MXU floor**: a plain bf16 matmul of the same shape — the physically
  unbeatable time for the distance pass,
* **fused_shortlist** alone across a (bm, bn) block-size grid,
* **full fast path** (shortlist + top-k + exact f32 rescore) and the
  exact path, for contrast.

Usage: ``python bench/profile_knn.py [--m 10000 --n 1000000 --d 128]``.
Prints one JSON line per measurement; effective TFLOP/s uses
``2·m·n·d / t``.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp
import numpy as np


def _arg(name, default):
    if name in sys.argv:
        return int(sys.argv[sys.argv.index(name) + 1])
    return default


# one timing protocol for every bench file (see ann.fetch docstring)
from ann import fetch, measure_qps, single_latency


def pipelined(fn, depth: int = 8) -> float:
    """Per-call seconds with the device queue kept full."""
    return 1.0 / measure_qps(fn, 1, reps=depth)


def single(fn, reps: int = 3) -> float:
    return single_latency(fn, reps)


def main() -> None:
    m = _arg("--m", 10_000)
    n = _arg("--n", 1_000_000)
    d = _arg("--d", 128)
    flops = 2.0 * m * n * d

    key = jax.random.PRNGKey(0)
    kq, kd = jax.random.split(key)
    db = jax.block_until_ready(jax.random.normal(kd, (n, d), jnp.float32))
    q = jax.block_until_ready(jax.random.normal(kq, (m, d), jnp.float32))
    dbb = jax.block_until_ready(db.astype(jnp.bfloat16))
    qb = jax.block_until_ready(q.astype(jnp.bfloat16))
    yn = jax.block_until_ready(jnp.sum(db.astype(jnp.float32) ** 2, axis=1))

    def emit(case, t, extra=None):
        print(json.dumps({
            "case": case, "ms": round(t * 1e3, 2),
            "tflops": round(flops / t / 1e12, 1),
            **(extra or {})}), flush=True)

    # MXU floor: the distance matmul with a tiny reduction epilogue so the
    # (m, n) product never transfers (sum ~ one f32 per row)
    mm = jax.jit(lambda a, b: jnp.min(
        jnp.dot(a, b.T, preferred_element_type=jnp.float32), axis=1))
    t = pipelined(lambda: mm(qb, dbb))
    emit("matmul_floor_bf16", t)

    # fused_shortlist block-size sweep
    from raft_tpu.ops.pallas.fused_l2_topk import fused_shortlist

    for bm in (256, 512, 1024):
        for bn in (1024, 2048):
            try:
                t = pipelined(lambda bm=bm, bn=bn: fused_shortlist(
                    qb, dbb, yn, bm=bm, bn=bn))
            except Exception as e:  # noqa: BLE001
                print(json.dumps({"case": f"shortlist_bm{bm}_bn{bn}",
                                  "error": str(e)[:120]}), flush=True)
                continue
            emit(f"shortlist_bm{bm}_bn{bn}", t)

    # full fast path (current defaults) + RTT split
    from raft_tpu.neighbors.brute_force import _fast_knn_impl, _knn_impl

    fast = lambda: _fast_knn_impl(q, db, 10, "sqeuclidean", 64, 1024, 1024)
    t1 = single(fast)
    tp = pipelined(fast)
    emit("fast_full", tp, {
        "single_dispatch_ms": round(t1 * 1e3, 2),
        "tunnel_overhead_ms": round((t1 - tp) * 1e3, 2),
        "qps_pipelined": round(m / tp, 0)})

    t = pipelined(lambda: _knn_impl(q, db, 10, "sqeuclidean", 65536), depth=2)
    emit("exact_full", t)


if __name__ == "__main__":
    main()
