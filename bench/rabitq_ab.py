"""RaBitQ vs IVF-PQ A/B — the ISSUE 13 acceptance artifact.

Two claims, measured on one clustered 200k×64 corpus (the
``bench/ann.py`` surrogate protocol, same timing/sync discipline):

* **search** — the rabitq 1-bit estimator scan + exact rerank beats the
  ivf_pq recon tier's QPS at matched recall@10 ≥ 0.95.  The rabitq arm
  sweeps ``n_probes`` × ``rerank_k``; the pq side sweeps the recon tier
  AND two ``refine`` serving setups (ratio 8/16 — the recon tier alone
  saturates near recall 0.57 on clustered data, so the refine arms are
  what gives pq a fighting chance at the floor), and the best pq point
  across ALL arms is the baseline — an honest comparison, not a
  strawman.
* **build** — the codebook-free rabitq build moves more rows/s than
  ``ivf_pq.build`` under identical coarse-training settings (no PQ
  sub-kmeans, no code assignment sweep).

Memory at rest is matched within ~25 %: rabitq stores d/8 = 8 B codes
+ 12 B correction scalars per vector (20 B) vs pq_dim=16 × 8-bit codes
(16 B); both serving setups additionally keep raw vectors for the
exact stage (rabitq's rerank slab / pq's refine dataset).  Per-vector
bytes ride the artifact so the trade is explicit.

    python bench/rabitq_ab.py [--quick] [--cpu]

Writes ``bench/RABITQ_<BACKEND>.json``.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax

from _platform import pin_backend

# MUST precede any backend use (see _platform.py: the axon plugin's
# sitecustomize overrides a bare JAX_PLATFORMS env var)
pin_backend(sys.argv)

import numpy as np

from ann import (best_at_recall, default_n_lists, ground_truth,
                 make_clustered, sweep_ivf_pq, sweep_ivf_rabitq)
from raft_tpu.neighbors import ivf_pq, ivf_rabitq

ROWS, DIM, NQ, K = 200_000, 64, 2000, 10
QUICK_ROWS = 20_000
RECALL_FLOOR = 0.95
PQ_DIM, PQ_BITS = 16, 8
PROBE_GRID = [4, 8, 16, 32]
# 0 = the tuned-table/heuristic default; the wider widths trade exact-
# gather rows for probes (rerank_k is the cheaper recall dial — see
# docs/tuning_guide.md)
RERANK_GRID = [0, 160, 320]
REFINE_RATIOS = [8, 16]
# identical coarse-training budget for the build race
TRAIN_FRACTION, TRAIN_ITERS = 0.05, 10


def _bytes_per_vector(d: int) -> dict:
    return {
        "rabitq_codes": d // 8,
        "rabitq_correction_scalars": 12,          # sabs + res_norm + cdot f32
        "rabitq_total_quantized": d // 8 + 12,
        "pq_codes": PQ_DIM * PQ_BITS // 8,
        "raw_rerank_row_f32": 4 * d,              # both serving setups
    }


def main() -> None:
    quick = "--quick" in sys.argv
    rows = QUICK_ROWS if quick else ROWS
    backend = jax.default_backend()
    n_clusters = max(64, rows // 1000)
    x = make_clustered(rows, DIM, n_clusters, seed=0, scale=2.0)
    q = make_clustered(NQ, DIM, n_clusters, seed=0, scale=2.0, point_seed=1)
    gt = ground_truth(q, x, K)
    n_lists = default_n_lists(rows)

    # --- build race (end-to-end build(), identical coarse training) ---
    rp = ivf_rabitq.IvfRabitqIndexParams(
        n_lists=n_lists, kmeans_trainset_fraction=TRAIN_FRACTION,
        kmeans_n_iters=TRAIN_ITERS, seed=0)
    pp = ivf_pq.IvfPqIndexParams(
        n_lists=n_lists, pq_dim=PQ_DIM, pq_bits=PQ_BITS,
        kmeans_trainset_fraction=TRAIN_FRACTION,
        kmeans_n_iters=TRAIN_ITERS, seed=0)

    def _timed_build(build, p):
        t0 = time.perf_counter()
        index = build(x, p)
        jax.block_until_ready(index.counts)
        return index, time.perf_counter() - t0

    # warm both builder programs once so the race times steady-state
    # streaming, not first-call compilation (both arms get the same deal)
    warm_rows = min(rows, 20_000)
    _timed_build(lambda xx, p: ivf_rabitq.build(x[:warm_rows], p), rp)
    _timed_build(lambda xx, p: ivf_pq.build(x[:warm_rows], p), pp)
    rq_index, rq_build_s = _timed_build(ivf_rabitq.build, rp)
    pq_index, pq_build_s = _timed_build(ivf_pq.build, pp)
    build = {
        "rows": rows, "n_lists": n_lists,
        "train_fraction": TRAIN_FRACTION, "train_iters": TRAIN_ITERS,
        "rabitq_s": round(rq_build_s, 3),
        "ivf_pq_s": round(pq_build_s, 3),
        "rabitq_rows_per_s": round(rows / rq_build_s),
        "ivf_pq_rows_per_s": round(rows / pq_build_s),
        "speedup": round(pq_build_s / rq_build_s, 3),
    }
    print(json.dumps({"build": build}), flush=True)

    # --- search race -------------------------------------------------
    rq_curve = []
    for rk in RERANK_GRID:
        for pt in sweep_ivf_rabitq(rq_index, q, gt, K, PROBE_GRID,
                                   rerank_k=rk):
            rq_curve.append(pt)
            print(json.dumps({"config": "ivf_rabitq", **pt}), flush=True)
    pq_recon = sweep_ivf_pq(pq_index, q, gt, K, PROBE_GRID)
    for pt in pq_recon:
        print(json.dumps({"config": "ivf_pq_recon", **pt}), flush=True)
    pq_refine = []
    for ratio in REFINE_RATIOS:
        for pt in sweep_ivf_pq(pq_index, q, gt, K, PROBE_GRID,
                               refine_dataset=x, refine_ratio=ratio):
            pq_refine.append(dict(pt, refine_ratio=ratio))
            print(json.dumps({"config": f"ivf_pq_recon_refine{ratio}",
                              **pt}), flush=True)

    rq_best = best_at_recall(rq_curve, RECALL_FLOOR)
    pq_recon_best = best_at_recall(pq_recon, RECALL_FLOOR)
    pq_bests = [b for b in (pq_recon_best,
                            best_at_recall(pq_refine, RECALL_FLOOR))
                if b is not None]
    pq_best = max(pq_bests, key=lambda b: b["qps"]) if pq_bests else None

    # the ISSUE baseline is the recon tier; the committed claim is the
    # stronger one — faster than the best pq arm that reaches the floor
    # at all (a baseline that never reaches the floor loses by DNF)
    qps_ok = (rq_best is not None
              and (pq_best is None or rq_best["qps"] > pq_best["qps"]))
    build_ok = build["rabitq_rows_per_s"] >= build["ivf_pq_rows_per_s"]
    out = {
        "bench": "rabitq_ab",
        "backend": backend,
        "mode": "quick" if quick else "full",
        "dataset": {"rows": rows, "dim": DIM, "queries": NQ, "k": K,
                    "n_clusters": n_clusters, "clustered": True},
        "recall_floor": RECALL_FLOOR,
        "bytes_per_vector": _bytes_per_vector(DIM),
        "build": build,
        "search": {
            "ivf_rabitq": rq_curve,
            "ivf_pq_recon": pq_recon,
            "ivf_pq_recon_refine": pq_refine,
        },
        "best_at_floor": {
            "ivf_rabitq": rq_best,
            "ivf_pq": pq_best,
            "ivf_pq_recon_only": pq_recon_best,
            "pq_recon_reaches_floor": pq_recon_best is not None,
        },
        "acceptance": {
            "rabitq_qps_beats_pq_at_floor": qps_ok,
            "rabitq_build_rows_per_s_ge_pq": build_ok,
        },
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        f"RABITQ_{backend.upper()}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {path}")
    print(json.dumps({"acceptance": out["acceptance"],
                      "best": out["best_at_floor"]}), flush=True)


if __name__ == "__main__":
    main()
