"""Out-of-core tier acceptance bench — the FusionANNS-style memory
split under an explicit device budget.

The scenario ISSUE 14 pins: a corpus whose flat f32 slab does NOT fit
the device budget (10M×64 f32 = 2.56 GB vs a 1 GB budget) served by the
``ooc`` tier, whose device residency is only the packed RaBitQ code
slabs + centroids while the raw rows stay host-side in the mmap-backed
shard store.  The bench **asserts** the budget story instead of just
narrating it:

* ``flat_slab_bytes > device_budget``  (the flat tier is inadmissible),
* ``resident_bytes + slab_budget <= device_budget``  (the ooc tier fits
  with its staged-rerank headroom),
* ``max_put_bytes <= staged-chunk bound``  (measured via
  ``ooc.transfer_stats()`` — the search loop really never staged more
  than one query chunk's slab),
* best recall@k ≥ ``--recall-floor`` somewhere on the sweep.

Each sweep point runs the SAME searches with ``overlap=True`` and
``overlap=False`` (the ``device_prefetch`` double-buffer A/B) — results
are bit-identical (tests/test_ooc.py), so the delta is pure wall-clock.

    python bench/ooc_bench.py [--rows 10000000] [--cpu]

Writes ``bench/OOC_<BACKEND>.json``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

from _platform import pin_backend

pin_backend(sys.argv)

import jax
import numpy as np

from ann import fetch, measure_qps

from raft_tpu.neighbors import ooc
from raft_tpu.neighbors.ivf_rabitq import resolve_rerank_k
from raft_tpu.stats import neighborhood_recall


def make_clustered_host(rows: int, dim: int, n_clusters: int, seed: int,
                        chunk: int = 1 << 20, point_seed: int = 0,
                        spread: float = 1.0, scale: float = 4.0):
    """Clustered synthetic data built host-side in chunks — the bench
    must not materialize the corpus on device (that would be the flat
    slab the budget forbids)."""
    rng_c = np.random.default_rng(seed)
    centers = (rng_c.standard_normal((n_clusters, dim)) * scale
               ).astype(np.float32)
    rng_p = np.random.default_rng((seed + 1) * 1_000_003 + point_seed)
    out = np.empty((rows, dim), np.float32)
    for lo in range(0, rows, chunk):
        hi = min(rows, lo + chunk)
        cid = rng_p.integers(0, n_clusters, size=hi - lo)
        out[lo:hi] = centers[cid]
        out[lo:hi] += spread * rng_p.standard_normal(
            (hi - lo, dim)).astype(np.float32)
    return out


def chunked_ground_truth(queries, database, k: int,
                         chunk: int = 1 << 20) -> np.ndarray:
    """Exact top-k over a host-resident corpus, one device chunk at a
    time — the oracle obeys the same device budget as the index."""
    import jax.numpy as jnp

    q = jnp.asarray(queries)
    best_v = None
    best_i = None

    @jax.jit
    def merge(bv, bi, dv, di):
        v = jnp.concatenate([bv, dv], axis=1)
        i = jnp.concatenate([bi, di], axis=1)
        top_v, pos = jax.lax.top_k(-v, k)
        return -top_v, jnp.take_along_axis(i, pos, axis=1)

    for lo in range(0, database.shape[0], chunk):
        hi = min(database.shape[0], lo + chunk)
        dv, di = ground_truth_chunk(q, jnp.asarray(database[lo:hi]), k)
        di = di + lo
        if best_v is None:
            best_v, best_i = dv, di
        else:
            best_v, best_i = merge(best_v, best_i, dv, di)
    fetch((best_v, best_i))
    return np.asarray(best_i)


def ground_truth_chunk(q, db, k):
    from functools import partial

    @partial(jax.jit, static_argnames=("kk",))
    def run(q, db, kk):
        qn = (q * q).sum(axis=1)[:, None]
        yn = (db * db).sum(axis=1)[None, :]
        d = qn + yn - 2.0 * q @ db.T
        top_v, top_i = jax.lax.top_k(-d, kk)
        return -top_v, top_i

    return run(q, db, k)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--rows", type=int, default=10_000_000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--queries", type=int, default=512)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--n-lists", type=int, default=1024)
    ap.add_argument("--device-budget", type=int, default=1 << 30,
                    help="total device bytes the tier may use")
    ap.add_argument("--slab-budget", type=int, default=256 << 20,
                    help="staged-rerank headroom within the budget")
    ap.add_argument("--rerank-k", type=int, default=0,
                    help="0 = tuned table / heuristic")
    ap.add_argument("--sweep", default="16,32,64")
    ap.add_argument("--clusters", type=int, default=0,
                    help="0 = rows/1000 (local density, and therefore the "
                         "rerank budget a 1-bit estimator needs to reach a "
                         "given recall, stays constant as --rows scales)")
    ap.add_argument("--recall-floor", type=float, default=0.95)
    ap.add_argument("--train-fraction", type=float, default=0.01)
    ap.add_argument("--train-iters", type=int, default=5)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--store-path", default=None)
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    backend = jax.default_backend()
    rows, dim, nq, k = args.rows, args.dim, args.queries, args.k
    flat_bytes = rows * dim * 4
    if flat_bytes <= args.device_budget:
        raise SystemExit(
            f"scenario broken: flat slab {flat_bytes} fits the device "
            f"budget {args.device_budget} — raise --rows or lower the "
            f"budget (the bench exists to show the flat tier is "
            f"inadmissible)")

    n_clusters = args.clusters or max(64, rows // 1_000)
    t0 = time.time()
    x = make_clustered_host(rows, dim, n_clusters, args.seed)
    q = make_clustered_host(nq, dim, n_clusters, args.seed, point_seed=1)
    gen_s = round(time.time() - t0, 1)
    print(json.dumps({"dataset": {"rows": rows, "dim": dim, "queries": nq,
                                  "clusters": n_clusters, "gen_s": gen_s,
                                  "flat_slab_bytes": flat_bytes}}),
          flush=True)

    store_root = args.store_path or tempfile.mkdtemp(prefix="ooc_bench_")
    own_store = args.store_path is None
    p = ooc.OocIndexParams(n_lists=args.n_lists,
                           kmeans_trainset_fraction=args.train_fraction,
                           kmeans_n_iters=args.train_iters, seed=args.seed)
    t0 = time.time()
    index = ooc.build(x, p, store_path=os.path.join(store_root, "shards"))
    build_s = round(time.time() - t0, 1)
    resident = int(index.resident_bytes)
    print(json.dumps({"build": {
        "build_s": build_s, "n_lists": args.n_lists,
        "list_cap": int(index.list_cap),
        "resident_bytes": resident,
        "host_bytes": int(index.host_bytes),
        "bytes_per_vec_device": round(resident / rows, 2)}}), flush=True)

    if resident + args.slab_budget > args.device_budget:
        raise SystemExit(
            f"budget violated: resident {resident} + slab_budget "
            f"{args.slab_budget} > device budget {args.device_budget}")

    t0 = time.time()
    gt = chunked_ground_truth(q, x, k)
    gt_s = round(time.time() - t0, 1)
    print(json.dumps({"gt_s": gt_s}), flush=True)

    probes = [int(v) for v in args.sweep.split(",")]
    curve = []
    max_put_seen = 0
    for n_probes in probes:
        rk = resolve_rerank_k(args.rerank_k, k, n_probes, index.list_cap)
        point = {"n_probes": n_probes, "rerank_k": rk}
        for overlap in (True, False):
            sp = ooc.OocSearchParams(
                n_probes=n_probes, rerank_k=args.rerank_k,
                slab_budget=args.slab_budget, overlap=overlap)
            run = lambda sp=sp: ooc.search(index, q, k, sp)
            if overlap:
                ids = np.asarray(fetch(run())[1])
                point["recall"] = round(
                    float(neighborhood_recall(ids, gt)), 4)
            ooc.reset_transfer_stats()
            qps = measure_qps(run, nq, reps=2, rounds=2)
            max_put_seen = max(max_put_seen,
                               ooc.transfer_stats()["max_put_bytes"])
            point["qps_overlap" if overlap else "qps_no_overlap"] = \
                round(qps, 1)
        point["overlap_speedup"] = round(
            point["qps_overlap"] / point["qps_no_overlap"], 3)
        curve.append(point)
        print(json.dumps(point), flush=True)

    assert max_put_seen <= args.slab_budget + nq * dim * 4, \
        (max_put_seen, args.slab_budget)
    ok = [pt for pt in curve if pt["recall"] >= args.recall_floor]
    if not ok:
        raise SystemExit(f"recall floor {args.recall_floor} not reached: "
                         f"{[pt['recall'] for pt in curve]}")
    best = max(ok, key=lambda pt: pt["qps_overlap"])

    out = {
        "bench": "ooc",
        "backend": backend,
        "rows": rows, "dim": dim, "queries": nq, "k": k,
        "n_lists": args.n_lists,
        "device_budget": args.device_budget,
        "slab_budget": args.slab_budget,
        "flat_slab_bytes": flat_bytes,
        "resident_bytes": resident,
        "host_bytes": int(index.host_bytes),
        "bytes_per_vec_device": round(resident / rows, 2),
        "budget_check": {
            "flat_fits_budget": False,
            "ooc_fits_budget": True,
            "max_put_bytes_observed": int(max_put_seen),
        },
        "build_s": build_s, "gt_s": gt_s,
        "recall_floor": args.recall_floor,
        "results": curve,
        "best": best,
        "note": ("overlap on/off is the device_prefetch double-buffer "
                 "A/B over bit-identical results; max_put_bytes is the "
                 "largest single H2D staging put the search loop made "
                 "(ooc.transfer_stats), proving no hidden full-slab "
                 "device_put"),
    }
    path = args.out or os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        f"OOC_{backend.upper()}.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    print(f"wrote {path}")
    if own_store:
        shutil.rmtree(store_root, ignore_errors=True)


if __name__ == "__main__":
    main()
