"""Blocked-scan A/B driver for the shared ``ops/blocked_scan.py`` core.

Times every neighbors family's blocked search path through the public
API, so the same script measures the tree before and after an engine
refactor.  Arms accumulate into one JSON: run once on the pre-refactor
tree with ``--tag per_engine``, once on the refactored tree with
``--tag shared_core``, and the script emits the ratio table whenever
both arms are present.  The committed CPU acceptance artifact is
``bench/FUSED_SCAN_CPU.json``:

    python bench/fused_scan.py --cpu --tag per_engine  --out /tmp/FUSED_SCAN_CPU.json
    ... refactor ...
    python bench/fused_scan.py --cpu --tag shared_core --out /tmp/FUSED_SCAN_CPU.json

On CPU the fused Pallas arm runs in ``interpret=True`` mode, which is a
parity check, not a performance number — it is recorded under
``fused_interpret`` with that caveat, and the real MXU timing stays
staged in ``scripts/tpu_jobs_r11.sh``.
"""

from __future__ import annotations

import datetime
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax

from _platform import pin_backend

# MUST precede any backend use (see tune_select_k.py)
pin_backend(sys.argv)

import numpy as np

from _timing import timeit as _time
from ann import make_clustered
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq

DIM, NQ, K = 64, 256, 10
IVF_ROWS, IVF_LISTS, N_PROBES, PROBE_BLOCK = 60_000, 128, 32, 8
BF_ROWS = 20_000
CAGRA_ROWS, ITOPK, WIDTH = 20_000, 64, 4


def kernel_sha() -> str:
    """Hash of every source file the timed paths run through (missing
    files — e.g. ``ops/blocked_scan.py`` on the pre-refactor tree — are
    skipped so the before/after arms get distinct, honest shas)."""
    import hashlib

    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    h = hashlib.sha256()
    for rel in ("raft_tpu/neighbors/ivf_flat.py",
                "raft_tpu/neighbors/ivf_pq.py",
                "raft_tpu/neighbors/cagra.py",
                "raft_tpu/neighbors/brute_force.py",
                "raft_tpu/neighbors/_packing.py",
                "raft_tpu/matrix/select_k.py",
                "raft_tpu/ops/blocked_scan.py",
                "raft_tpu/ops/pallas/fused_scan.py",
                "raft_tpu/ops/pallas/gate.py"):
        try:
            with open(os.path.join(root, rel), "rb") as f:
                h.update(f.read())
        except OSError:
            h.update(b"<absent>")
    return h.hexdigest()[:16]


def _measure_arms() -> dict:
    arms: dict = {}
    rng_q = 0.1

    x = make_clustered(IVF_ROWS + NQ, DIM, 256, seed=3, scale=2.0)
    db, q = x[:IVF_ROWS], jax.device_put(x[IVF_ROWS:])

    fi = ivf_flat.build(db, ivf_flat.IvfFlatIndexParams(
        n_lists=IVF_LISTS, list_cap_ratio=1.5,
        kmeans_trainset_fraction=0.05, seed=0))
    fp = ivf_flat.IvfFlatSearchParams(n_probes=N_PROBES,
                                      probe_block=PROBE_BLOCK)
    arms["ivf_flat"] = _time(lambda: ivf_flat.search(fi, q, K, fp))
    print(f"ivf_flat        {arms['ivf_flat'] * 1e3:8.1f} ms")

    pi = ivf_pq.build(db, ivf_pq.IvfPqIndexParams(
        n_lists=IVF_LISTS, pq_dim=16, list_cap_ratio=1.5,
        kmeans_trainset_fraction=0.05, seed=0))
    for mode in ("recon", "lut"):
        pp = ivf_pq.IvfPqSearchParams(n_probes=N_PROBES, mode=mode,
                                      probe_block=PROBE_BLOCK)
        arms[f"ivf_pq_{mode}"] = _time(lambda: ivf_pq.search(pi, q, K, pp))
        print(f"ivf_pq_{mode:5s}    {arms[f'ivf_pq_{mode}'] * 1e3:8.1f} ms")

    xb = make_clustered(BF_ROWS + NQ, DIM, 64, seed=3, scale=2.0)
    bdb, bq = jax.device_put(xb[:BF_ROWS]), jax.device_put(xb[BF_ROWS:])
    arms["brute_force"] = _time(lambda: brute_force.knn(bdb, bq, K))
    print(f"brute_force     {arms['brute_force'] * 1e3:8.1f} ms")

    xc = make_clustered(CAGRA_ROWS + NQ, DIM, 100, seed=3, scale=2.0)
    cdb, cq = xc[:CAGRA_ROWS], jax.device_put(xc[CAGRA_ROWS:])
    ci = cagra.build(cdb, cagra.CagraIndexParams(
        intermediate_graph_degree=64, graph_degree=32))
    cp = cagra.CagraSearchParams(itopk_size=ITOPK, search_width=WIDTH,
                                 search_impl="frontier")
    arms["cagra"] = _time(lambda: cagra.search(ci, cq, K, cp))
    print(f"cagra           {arms['cagra'] * 1e3:8.1f} ms")
    del rng_q
    return arms


def _fused_interpret_check() -> dict | None:
    """Tiny interpret-mode run of the fused slab kernel (post-refactor
    trees only): records that the arm exists and agrees with the XLA
    fold — wall-clock in interpret mode is NOT a perf number."""
    try:
        from raft_tpu.ops.blocked_scan import fold_topk
        from raft_tpu.ops.pallas.fused_scan import fused_slab_topk
    except ImportError:
        return None
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    nq, c, d, k = 8, 256, DIM, K
    vecs = jnp.asarray(rng.standard_normal((nq, c, d)), jnp.float32)
    qv = jnp.asarray(rng.standard_normal((nq, d)), jnp.float32)
    base = jnp.sum(vecs.astype(jnp.float32) ** 2, axis=-1)
    t = _time(lambda: fused_slab_topk(vecs, base, qv, interpret=True))
    sv, spos = fused_slab_topk(vecs, base, qv, interpret=True)
    init_v = jnp.full((nq, k), jnp.inf, jnp.float32)
    init_i = jnp.full((nq, k), -1, jnp.int32)
    fv, fo = fold_topk(init_v, init_i, sv, spos, k)
    exact = base - 2.0 * jnp.einsum("ncd,nd->nc", vecs, qv,
                                    preferred_element_type=jnp.float32)
    ev, ei = jax.lax.top_k(-exact, k)
    agree = float(np.mean([len(set(np.asarray(fo[i])) & set(np.asarray(ei[i])))
                           for i in range(nq)])) / k
    return {"interpret_s": t, "nq": nq, "c": c, "d": d, "k": k,
            "shortlist_recall_vs_exact": round(agree, 4),
            "note": "interpret=True parity probe; not a perf number — "
                    "MXU timing staged in scripts/tpu_jobs_r11.sh"}


def main() -> None:
    tag = "shared_core"
    if "--tag" in sys.argv:
        tag = sys.argv[sys.argv.index("--tag") + 1]
    backend = jax.default_backend()
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       f"FUSED_SCAN_{backend.upper()}.json")
    if "--out" in sys.argv:
        out = sys.argv[sys.argv.index("--out") + 1]

    doc: dict = {"backend": backend, "arms": {}}
    try:
        with open(out) as f:
            prior = json.load(f)
        if prior.get("backend") == backend:
            doc = prior
    except (OSError, ValueError):
        pass

    print(f"backend={backend} tag={tag}")
    doc["arms"][tag] = _measure_arms()
    doc["date"] = datetime.date.today().isoformat()
    shas = doc.get("kernel_sha")
    shas = dict(shas) if isinstance(shas, dict) else {}
    shas[tag] = kernel_sha()
    doc["kernel_sha"] = shas
    doc["config"] = {"dim": DIM, "nq": NQ, "k": K, "ivf_rows": IVF_ROWS,
                     "n_lists": IVF_LISTS, "n_probes": N_PROBES,
                     "probe_block": PROBE_BLOCK, "bf_rows": BF_ROWS,
                     "cagra_rows": CAGRA_ROWS, "itopk": ITOPK,
                     "search_width": WIDTH}

    fused = _fused_interpret_check()
    if fused is not None:
        doc["fused_interpret"] = fused

    per, shared = doc["arms"].get("per_engine"), doc["arms"].get("shared_core")
    if per and shared:
        doc["ab"] = {
            fam: {"per_engine_s": per[fam], "shared_core_s": shared[fam],
                  "speedup": round(per[fam] / shared[fam], 3)}
            for fam in sorted(set(per) & set(shared))}
        doc["note"] = ("shared_core is the ops/blocked_scan.py refactor; "
                       "speedup >= ~1.0 means the shared core is no slower "
                       "than the per-engine scan paths it replaced")
        for fam, row in doc["ab"].items():
            print(f"A/B {fam:12s} {row['per_engine_s'] * 1e3:8.1f} ms → "
                  f"{row['shared_core_s'] * 1e3:8.1f} ms "
                  f"(x{row['speedup']:.3f})")

    with open(out, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {os.path.normpath(out)}")


if __name__ == "__main__":
    main()
