"""North-star ANN benchmark harness — QPS@recall curves for IVF-PQ and
CAGRA at DEEP-10M-class scale (``BASELINE.json`` configs[3-4]; gating
metric = ``stats.neighborhood_recall``, the role of
``/root/reference/cpp/include/raft/stats/neighborhood_recall.cuh:77``; the
harness itself is the raft-ann-bench role, removed upstream with the cuVS
migration).

Dataset: DEEP files are not available in-image (zero egress), so the
harness synthesizes a clustered dataset of the same shape (96-dim, like
DEEP) — points drawn around ``sqrt(n)``-ish gaussian centers, the standard
ANN-benchmark surrogate.  IID gaussian would be the PQ worst case and no
graph structure would exist; clustered data matches how real embedding
corpora behave.

All timing is pipelined-dispatch wall time with one host-fetch sync
(``jax.block_until_ready`` returns at enqueue on the remote-TPU tunnel),
QPS = queries / (batch wall / reps).
"""

from __future__ import annotations

import time
from functools import partial
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "make_clustered",
    "ground_truth",
    "fetch",
    "measure_qps",
    "measure_point",
    "single_latency",
    "sweep_ivf_flat",
    "sweep_ivf_pq",
    "sweep_ivf_rabitq",
    "sweep_ooc",
    "sweep_cagra",
    "best_at_recall",
]


def make_clustered(n: int, d: int, n_clusters: int, seed: int = 0,
                   spread: float = 1.0, scale: float = 4.0,
                   chunk: int = 1 << 20, point_seed: int = 0) -> jax.Array:
    """Clustered synthetic dataset, generated on device in chunks
    (never materializes a second full-size temporary).  ``point_seed``
    varies the points while keeping the same cluster centers — held-out
    query sets come from the same distribution as the database."""
    chunk = min(chunk, n)
    key = jax.random.PRNGKey(seed)
    kc, kp = jax.random.split(key)
    kp = jax.random.fold_in(kp, point_seed)
    centers = jax.random.normal(kc, (n_clusters, d), jnp.float32) * scale

    @partial(jax.jit, static_argnames=("rows",))
    def gen_chunk(k, rows):
        ka, kb = jax.random.split(k)
        cid = jax.random.randint(ka, (rows,), 0, n_clusters)
        return centers[cid] + spread * jax.random.normal(
            kb, (rows, d), jnp.float32)

    # donated in-place writes into an exact-size buffer: peak device memory
    # stays dataset + one chunk (no second full-size temporary)
    write = jax.jit(
        lambda buf, pts, lo: jax.lax.dynamic_update_slice(buf, pts, (lo, 0)),
        donate_argnums=0)
    out = jnp.zeros((n, d), jnp.float32)
    for i, lo in enumerate(range(0, n, chunk)):
        rows = min(chunk, n - lo)
        pts = gen_chunk(jax.random.fold_in(kp, i), rows)
        out = write(out, pts, lo)
    return out


def fetch(o):
    """Host-fetch every output leaf — the only reliable completion barrier
    on the remote-TPU tunnel (``jax.block_until_ready`` returns at
    enqueue).  The single home of the sync protocol; bench.py and
    bench/profile_knn.py reuse it so their numbers stay comparable."""
    for leaf in jax.tree_util.tree_leaves(o):
        np.asarray(leaf)
    return o


_fetch = fetch  # back-compat alias


def ground_truth(queries, database, k: int, tile: int = 65536,
                 metric: str = "sqeuclidean"):
    """Exact top-k ids (untimed) for the recall gate — same metric as the
    index under test, or every recall number is meaningless."""
    from raft_tpu.neighbors.brute_force import _knn_impl

    _, gt = _knn_impl(queries, database, k, metric,
                      min(tile, database.shape[0]))
    return np.asarray(gt)


def measure_qps(run, nq: int, reps: int = 4, rounds: int = 2) -> float:
    """Pipelined throughput: dispatch ``reps`` calls, sync once — keeps the
    device queue full so the tunnel round trip amortizes."""
    fetch(run())  # compile + warm
    best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        outs = [run() for _ in range(reps)]
        for o in outs:
            fetch(o)
        best = min(best, (time.perf_counter() - t0) / reps)
    return nq / best


def single_latency(run, reps: int = 3) -> float:
    """Best-of-``reps`` single-dispatch seconds (includes one tunnel RTT);
    ``single_latency − nq/measure_qps`` estimates the link overhead."""
    fetch(run())
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fetch(run())
        best = min(best, time.perf_counter() - t0)
    return best


def _recall(ids, gt) -> float:
    from raft_tpu.stats import neighborhood_recall

    return float(neighborhood_recall(np.asarray(ids), gt))


def measure_point(run, gt, nq: int) -> dict:
    """One sweep point: run once for recall, then pipelined QPS — the
    single implementation behind every sweep (and the CLI's one-off
    modes), so all numbers share the timing protocol."""
    ids = fetch(run())[1]
    return {"recall": round(_recall(ids, gt), 4),
            "qps": round(measure_qps(run, nq), 1)}


def sweep_ivf_flat(index, queries, gt, k: int, probe_grid, *,
                   search_fn=None) -> List[dict]:
    """(n_probes → recall, qps) curve for IVF-Flat.  ``search_fn`` swaps
    the search implementation (e.g. ``partial(search_sharded, mesh=m)``)
    while keeping the sweep protocol identical."""
    from raft_tpu.neighbors import ivf_flat

    search_fn = search_fn or ivf_flat.search
    out = []
    nq = queries.shape[0]
    for n_probes in probe_grid:
        p = ivf_flat.IvfFlatSearchParams(n_probes=int(n_probes))
        run = lambda p=p: search_fn(index, queries, k, p)
        out.append({"n_probes": int(n_probes), **measure_point(run, gt, nq)})
    return out


def sweep_ivf_pq(index, queries, gt, k: int, probe_grid, *,
                 refine_dataset=None, refine_ratio: int = 4,
                 search_fn=None) -> List[dict]:
    """(n_probes → recall, qps) curve; with ``refine_dataset`` each search
    retrieves ``refine_ratio·k`` PQ candidates and exactly re-ranks them
    (the standard IVF-PQ serving setup; ``neighbors.refine``).
    ``search_fn`` swaps the search implementation (no-refine path only)."""
    from raft_tpu.neighbors import ivf_pq
    from raft_tpu.neighbors.refine import refine

    search_fn = search_fn or ivf_pq.search
    out = []
    nq = queries.shape[0]
    for n_probes in probe_grid:
        p = ivf_pq.IvfPqSearchParams(n_probes=int(n_probes), query_chunk=0)

        if refine_dataset is None:
            run = lambda p=p: search_fn(index, queries, k, p)
        else:
            def run(p=p):
                _, cand = ivf_pq.search(index, queries, refine_ratio * k, p)
                return refine(refine_dataset, queries, cand, k,
                              metric=index.metric)

        out.append({"n_probes": int(n_probes), **measure_point(run, gt, nq)})
    return out


def sweep_ivf_rabitq(index, queries, gt, k: int, probe_grid, *,
                     rerank_k: int = 0, search_fn=None) -> List[dict]:
    """(n_probes → recall, qps) curve for IVF-RaBitQ.  Rerank is built
    in (``rerank_k=0`` resolves from the tuned table / heuristic), so
    unlike ``sweep_ivf_pq`` there is no external refine stage — the
    returned distances are already exact over the survivors."""
    from raft_tpu.neighbors import ivf_rabitq

    search_fn = search_fn or ivf_rabitq.search
    out = []
    nq = queries.shape[0]
    for n_probes in probe_grid:
        p = ivf_rabitq.IvfRabitqSearchParams(
            n_probes=int(n_probes), rerank_k=int(rerank_k), query_chunk=0)
        run = lambda p=p: search_fn(index, queries, k, p)
        out.append({"n_probes": int(n_probes),
                    "rerank_k": ivf_rabitq.resolve_rerank_k(
                        int(rerank_k), k, int(n_probes), index.list_cap),
                    **measure_point(run, gt, nq)})
    return out


def sweep_ooc(index, queries, gt, k: int, probe_grid, *,
              rerank_k: int = 0, slab_budget: int = 256 << 20,
              overlap: bool = True, search_fn=None) -> List[dict]:
    """(n_probes → recall, qps) curve for the out-of-core tier.  Same
    shape as ``sweep_ivf_rabitq`` — the estimator scan is shared — but
    every rerank crosses the host round-trip, so the QPS column prices
    the fetch+overlap machinery, not just the device scan."""
    from raft_tpu.neighbors import ooc
    from raft_tpu.neighbors.ivf_rabitq import resolve_rerank_k

    search_fn = search_fn or ooc.search
    out = []
    nq = queries.shape[0]
    for n_probes in probe_grid:
        p = ooc.OocSearchParams(
            n_probes=int(n_probes), rerank_k=int(rerank_k), query_chunk=0,
            slab_budget=int(slab_budget), overlap=bool(overlap))
        run = lambda p=p: search_fn(index, queries, k, p)
        out.append({"n_probes": int(n_probes),
                    "rerank_k": resolve_rerank_k(
                        int(rerank_k), k, int(n_probes), index.list_cap),
                    **measure_point(run, gt, nq)})
    return out


def sweep_cagra(index, queries, gt, k: int, grid, seed: int = 0, *,
                search_fn=None) -> List[dict]:
    """((itopk, search_width) → recall, qps) curve.  ``search_fn`` swaps
    the search implementation (e.g. sharded)."""
    from raft_tpu.neighbors import cagra

    search_fn = search_fn or (
        lambda ix, q, kk, p: cagra.search(ix, q, kk, p, seed=seed))
    out = []
    nq = queries.shape[0]
    for itopk, width in grid:
        p = cagra.CagraSearchParams(itopk_size=int(itopk),
                                    search_width=int(width))
        run = lambda p=p: search_fn(index, queries, k, p)
        out.append({"itopk": int(itopk), "width": int(width),
                    **measure_point(run, gt, nq)})
    return out


def default_n_lists(n: int) -> int:
    """The usual IVF starting point (tuning guide): ``2·sqrt(n)``, floored
    at 64 — one home for the heuristic so the CLI and configs agree."""
    return max(64, int(2 * np.sqrt(n)))


def best_at_recall(curve: List[dict], floor: float = 0.95):
    """Highest-QPS point with recall ≥ floor (None if the curve never
    reaches it)."""
    ok = [pt for pt in curve if pt["recall"] >= floor]
    return max(ok, key=lambda pt: pt["qps"]) if ok else None
