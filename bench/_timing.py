"""Shared timing harness for the bench scripts.

Min-of-N wall time with a host-fetch barrier after every call:
``jax.block_until_ready`` returns at enqueue on the remote-TPU tunnel
backend, so fetching (small) outputs is the only reliable sync — the
same caveat bench.py documents.
"""

from __future__ import annotations

import time

import jax
import numpy as np

__all__ = ["sync", "timeit"]


def sync(out):
    """Force completion by fetching every output leaf to host."""
    for leaf in jax.tree_util.tree_leaves(out):
        np.asarray(leaf)
    return out


def timeit(fn, reps: int = 3) -> float:
    """Best-of-``reps`` seconds for ``fn()`` (one untimed warm-up/compile)."""
    sync(fn())
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        sync(fn())
        best = min(best, time.perf_counter() - t0)
    return best
