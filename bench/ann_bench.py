"""ANN benchmark CLI — the raft-ann-bench role (removed upstream with the
cuVS migration) rebuilt TPU-side: build an index from a dataset file, sweep
search parameters, and report {recall, qps} points as JSON lines.

Datasets load through :mod:`raft_tpu.io` (``.npy`` / ``.fvecs`` / ``.bvecs``
— SIFT/DEEP/GIST TexMex formats) or are synthesized (``synthetic:N×D``)
when no files are available.  Ground truth is computed exactly (or loaded
from an ``.ivecs``/``.npy`` file).

Examples::

    # SIFT-1M layout (base/query/groundtruth files)
    python bench/ann_bench.py ivf_pq --base sift_base.fvecs \
        --query sift_query.fvecs --gt sift_groundtruth.ivecs \
        --n-lists 4096 --pq-dim 64 --sweep 8,16,32,64 --refine 4

    # no dataset files: synthesize a DEEP-10M-class corpus
    python bench/ann_bench.py cagra --base synthetic:1000000x96 --k 10 \
        --sweep 32:4,64:4,64:8

Index kinds: ``brute_force`` | ``ivf_flat`` | ``ivf_pq`` | ``ivf_rabitq``
| ``ooc`` | ``cagra``.  ``ooc`` keeps only compact codes on device and
reranks through the mmap-backed host shard store (``--store-path``).
Every result line carries the config; the last line is a summary with the
best QPS at ``--recall-floor`` (default 0.95).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# persistent XLA executable cache (shared with bench.py): repeat runs
# on the same machine skip recompilation
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

from _platform import pin_backend  # e.g. RAFT_BENCH_PLATFORM=cpu for smoke tests

pin_backend()

import numpy as np

from ann import (best_at_recall, ground_truth, make_clustered, measure_point,
                 sweep_cagra, sweep_ivf_flat, sweep_ivf_pq, sweep_ivf_rabitq)


def parse_synthetic(spec: str):
    """``synthetic:NxD[:seed]`` → (n, d, seed)."""
    parts = spec.split(":")
    n, d = (int(v) for v in parts[1].lower().replace("×", "x").split("x"))
    return n, d, int(parts[2]) if len(parts) > 2 else 0


def load_matrix(spec: str, what: str, n_clusters: int = 0,
                dtype: str = "native"):
    """Dataset file (.npy/.fvecs/.bvecs) or ``synthetic:NxD[:seed]``.
    ``n_clusters`` (from the base spec) keeps held-out queries on the
    SAME cluster centers — make_clustered only shares centers across
    calls with equal ``n_clusters``.  ``dtype``: "native" keeps the file
    dtype (uint8 .bvecs rides the int8 MXU fast path and 4x-smaller
    lists), "f32" casts up, "uint8" quantizes synthetic data to 0..255
    (SIFT-style corpora)."""
    if spec.startswith("synthetic:"):
        n, d, seed = parse_synthetic(spec)
        out = make_clustered(n, d, n_clusters or max(64, n // 1000),
                             seed=seed, scale=2.0,
                             point_seed=1 if what == "query" else 0)
        if dtype == "uint8":
            import jax.numpy as jnp

            out = jnp.clip(jnp.round(out * 16.0 + 128.0), 0, 255
                           ).astype(jnp.uint8)
        return out
    from raft_tpu import io as rio

    ext = os.path.splitext(spec)[1]
    if ext == ".npy":
        out = rio.read_npy(spec)
    elif ext == ".fvecs":
        out = rio.read_fvecs(spec)
    elif ext == ".bvecs":
        out = rio.read_bvecs(spec)
    else:
        raise SystemExit(f"{what}: unsupported dataset format {ext!r}")
    if dtype == "uint8" and out.dtype != np.uint8:
        raise SystemExit(f"{what}: --dtype uint8 only quantizes synthetic: "
                         "specs; float file data has no canonical 0..255 "
                         "scale (use a .bvecs file or --dtype native/f32)")
    return out.astype(np.float32) if dtype == "f32" else out


def load_gt(spec, queries, base, k, metric):
    if spec is None:
        return ground_truth(queries, base, k, metric=metric)
    ext = os.path.splitext(spec)[1]
    if ext == ".ivecs":
        from raft_tpu import io as rio

        return np.asarray(rio.read_ivecs(spec))[:, :k]
    if ext == ".npy":
        return np.load(spec)[:, :k]
    raise SystemExit(f"gt: unsupported format {ext!r}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("index", choices=["brute_force", "ivf_flat", "ivf_pq",
                                      "ivf_rabitq", "ooc", "cagra"])
    ap.add_argument("--base", required=True, help="dataset file or synthetic:NxD")
    ap.add_argument("--query", default=None, help="query file (default: synthetic held-out / first 10k rows)")
    ap.add_argument("--gt", default=None, help="ground-truth ids file (default: computed exactly)")
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--metric", default="sqeuclidean")
    ap.add_argument("--n-lists", type=int, default=0, help="0 → 2·sqrt(n) rounded")
    ap.add_argument("--pq-dim", type=int, default=0, help="0 → d/2")
    ap.add_argument("--pq-bits", type=int, default=8, help="codebook bits (4..8)")
    ap.add_argument("--pack-codes", action="store_true",
                    help="4-bit packed code storage (requires --pq-bits<=4)")
    ap.add_argument("--refine", type=int, default=4, help="ivf_pq refine ratio (0 = off)")
    ap.add_argument("--rerank-k", type=int, default=0,
                    help="ivf_rabitq/ooc exact-rerank pool (0 = tuned table "
                         "/ heuristic)")
    ap.add_argument("--store-path", default=None,
                    help="ooc: directory for the host shard store "
                         "(default: a fresh temp dir)")
    ap.add_argument("--slab-budget", type=int, default=256 << 20,
                    help="ooc: staged-rerank device-bytes cap")
    ap.add_argument("--no-overlap", action="store_true",
                    help="ooc: disable prefetch overlap (A/B baseline)")
    ap.add_argument("--graph-degree", type=int, default=32)
    ap.add_argument("--sweep", default=None,
                    help="ivf: probe list '8,16,32'; cagra: 'itopk:width,...'")
    ap.add_argument("--recall-floor", type=float, default=0.95)
    ap.add_argument("--dtype", choices=("native", "f32", "uint8"),
                    default="native",
                    help="native: keep file dtype (uint8 .bvecs stays "
                         "uint8); f32: cast up; uint8: quantize synthetic "
                         "data SIFT-style")
    ap.add_argument("--chunked", action="store_true",
                    help="stream the build from host (out-of-core)")
    ap.add_argument("--sharded", type=int, default=0, metavar="S",
                    help="distributed build+search over an S-device mesh "
                         "(ivf_flat/ivf_pq/cagra)")
    args = ap.parse_args()

    base = load_matrix(args.base, "base", dtype=args.dtype)
    if args.query:
        q = load_matrix(args.query, "query", dtype=args.dtype)
    elif args.base.startswith("synthetic:"):
        nb, d0, seed = parse_synthetic(args.base)
        nq = min(10_000, nb // 10)
        # same n_clusters as the base → same centers, held-out points
        q = load_matrix(f"synthetic:{nq}x{d0}:{seed}", "query",
                        n_clusters=max(64, nb // 1000), dtype=args.dtype)
    else:
        q = np.asarray(base[:10_000])
    n, d = base.shape
    gt = load_gt(args.gt, q, base, args.k, args.metric)
    print(json.dumps({"dataset": {"rows": int(n), "dim": int(d),
                                  "queries": int(q.shape[0]), "k": args.k}}),
          flush=True)

    from ann import default_n_lists

    n_lists = args.n_lists or default_n_lists(n)
    mesh = None
    if args.sharded:
        if args.index == "brute_force":
            raise SystemExit("--sharded: use ivf_flat/ivf_pq/cagra (the "
                             "brute_force path here is single-device; "
                             "knn_sharded is the library API)")
        if args.chunked:
            raise SystemExit("--chunked and --sharded are exclusive: the "
                             "sharded build lays rows out per device, not "
                             "streamed from host")
        if args.index == "ivf_pq" and args.refine:
            print(json.dumps({"note": "--refine ignored with --sharded "
                              "(sharded sweep reports raw PQ recall)"}),
                  flush=True)
        import jax

        devs = jax.devices()[: args.sharded]
        if len(devs) < args.sharded:
            raise SystemExit(f"--sharded {args.sharded}: only {len(devs)} "
                             f"devices (for CPU simulation set XLA_FLAGS="
                             f"--xla_force_host_platform_device_count=S)")
        mesh = jax.sharding.Mesh(np.asarray(devs), ("shard",))
    from functools import partial

    t0 = time.time()
    build_s = None
    if args.index == "brute_force":
        from raft_tpu.neighbors import brute_force

        build_s = 0.0
        run = lambda: brute_force.knn(q, base, args.k, metric=args.metric,
                                      mode="fast")
        curve = [{"mode": "fast", **measure_point(run, gt, q.shape[0])}]
    elif args.index == "ooc":
        import tempfile

        from raft_tpu.neighbors import ooc
        from ann import sweep_ooc

        if mesh is not None:
            raise SystemExit("--sharded: ooc is single-device for now")
        store = args.store_path or tempfile.mkdtemp(prefix="ooc_store_")
        p = ooc.OocIndexParams(n_lists=n_lists, metric=args.metric)
        # the build is always streamed — out-of-core is the point
        index = ooc.build(np.asarray(base), p,
                          store_path=os.path.join(store, "shards"))
        build_s = round(time.time() - t0, 1)
        print(json.dumps({"ooc": {
            "resident_bytes": int(index.resident_bytes),
            "host_bytes": int(index.host_bytes),
            "store": store}}), flush=True)
        probes = ([int(v) for v in args.sweep.split(",")] if args.sweep
                  else [8, 16, 32, 64])
        curve = sweep_ooc(index, q, gt, args.k, probes,
                          rerank_k=args.rerank_k,
                          slab_budget=args.slab_budget,
                          overlap=not args.no_overlap)
    elif args.index in ("ivf_flat", "ivf_pq", "ivf_rabitq"):
        mod = __import__(f"raft_tpu.neighbors.{args.index}",
                         fromlist=[args.index])
        if args.index == "ivf_pq":
            p = mod.IvfPqIndexParams(n_lists=n_lists,
                                     pq_dim=args.pq_dim or d // 2,
                                     pq_bits=args.pq_bits,
                                     pack_codes=args.pack_codes,
                                     metric=args.metric)
        elif args.index == "ivf_rabitq":
            if mesh is not None:
                raise SystemExit("--sharded: ivf_rabitq is single-device "
                                 "for now (use ivf_flat/ivf_pq/cagra)")
            p = mod.IvfRabitqIndexParams(n_lists=n_lists, metric=args.metric)
        else:
            p = mod.IvfFlatIndexParams(n_lists=n_lists, metric=args.metric)
        if mesh is not None:
            index = mod.build_sharded(base, mesh, p)
        else:
            build = mod.build_chunked if args.chunked else mod.build
            src = np.asarray(base) if args.chunked else base
            index = build(src, p)
        build_s = round(time.time() - t0, 1)
        probes = ([int(v) for v in args.sweep.split(",")] if args.sweep
                  else [8, 16, 32, 64])
        search_fn = (partial(mod.search_sharded, mesh=mesh)
                     if mesh is not None else None)
        if args.index == "ivf_pq":
            curve = sweep_ivf_pq(
                index, q, gt, args.k, probes,
                refine_dataset=(base if args.refine and mesh is None else None),
                refine_ratio=max(args.refine, 1), search_fn=search_fn)
        elif args.index == "ivf_rabitq":
            curve = sweep_ivf_rabitq(index, q, gt, args.k, probes,
                                     rerank_k=args.rerank_k)
        else:
            curve = sweep_ivf_flat(index, q, gt, args.k, probes,
                                   search_fn=search_fn)
    else:  # cagra
        from raft_tpu.neighbors import cagra

        p = cagra.CagraIndexParams(
            intermediate_graph_degree=2 * args.graph_degree,
            graph_degree=args.graph_degree, metric=args.metric,
            build_algo="ivf" if n > 200_000 else "brute_force")  # routers auto
        grid = ([tuple(int(v) for v in pt.split(":")) for pt in args.sweep.split(",")]
                if args.sweep else [(32, 4), (64, 4), (64, 8)])
        if mesh is not None:
            index = cagra.build_sharded(base, mesh, p)
            search_fn = partial(cagra.search_sharded, mesh=mesh)
        else:
            index = cagra.build(base, p)
            search_fn = None
        build_s = round(time.time() - t0, 1)
        curve = sweep_cagra(index, q, gt, args.k, grid, search_fn=search_fn)

    for pt in curve:
        print(json.dumps({"config": args.index, **pt}), flush=True)
    best = best_at_recall(curve, args.recall_floor)
    print(json.dumps({"summary": {
        "index": args.index, "build_s": build_s,
        "recall_floor": args.recall_floor,
        "best": best,
        "qps_at_floor": None if best is None else best["qps"]}}), flush=True)


if __name__ == "__main__":
    main()
