"""Offline select_k dispatch tuning — the reference's trained-heuristic
pattern (``cpp/scripts/heuristics/select_k/generate_heuristic.ipynb``:
time every algorithm over a (rows, cols, k) grid, bake the winner table
into the dispatcher).

Run on the target backend (real TPU for production numbers):

    python bench/tune_select_k.py [--quick]

Writes ``raft_tpu/matrix/_select_k_table.json``, keyed by
``rows.bit_length():cols.bit_length():k.bit_length()`` buckets;
``matrix.select_k``'s ``kAuto`` consults it at call time (absent entries
fall back to ``lax.top_k``).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

# persistent XLA executable cache (shared with bench.py): repeat runs
# on the same machine skip recompilation
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax

from _platform import pin_backend

# MUST precede any backend use: a bare JAX_PLATFORMS env var is overridden
# by the axon plugin's sitecustomize, and an unpinned drill process dialing
# the (possibly wedged) tunnel is the documented wedge trigger
pin_backend(sys.argv)

import jax.numpy as jnp

from _timing import timeit as _time
from raft_tpu.matrix.select_k import SelectAlgo, select_k

GRID_ROWS = [256, 2048, 16384]
# 2048-wide / k=64 covers the brute-force fast path's shortlist cut
# ((m, 2·bn) → cand); the rest spans the select_k bench shapes
GRID_COLS = [1024, 2048, 16384, 131072]
GRID_K = [8, 32, 64, 128]
CANDIDATES = [SelectAlgo.kTopK, SelectAlgo.kPartialBitonic, SelectAlgo.kBinSelect]


def bucket_key(rows: int, cols: int, k: int) -> str:
    """The table/checkpoint bucket id — single home (the resume filter and
    the loop body must never desync on the key scheme)."""
    return f"{rows.bit_length()}:{cols.bit_length()}:{k.bit_length()}"


def kernel_sha() -> str:
    """Hash of the kernel + dispatch sources the table's timings depend
    on.  Recorded in the sidecar so "tuned against kernels that no longer
    exist" (the r3→r4 fori_loop staleness) is mechanically detectable, and
    used to scope the resume checkpoint."""
    import hashlib

    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    h = hashlib.sha256()
    for rel in ("raft_tpu/ops/pallas/select_k.py",
                "raft_tpu/ops/bin_select.py",
                "raft_tpu/matrix/select_k.py",
                "raft_tpu/ops/blocked_scan.py",
                "raft_tpu/ops/pallas/fused_scan.py",
                "raft_tpu/ops/pallas/gate.py"):
        with open(os.path.join(root, rel), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


# blocked-scan fused-arm sweep: candidates-per-block × k shape classes of
# the IVF engines (probe_block · list_cap candidate lanes per scan step)
SCAN_FAMILIES = ["ivf_flat", "ivf_pq"]
SCAN_CANDS = [1024, 4096, 16384]
SCAN_K = [8, 32, 128]


def tune_fused_scan(quick: bool) -> None:
    """Time the shared-core XLA slab scan against the fused Pallas arm
    (``scan_topk_fused``) per family : candidates-per-block : k bucket and
    write ``raft_tpu/ops/_scan_kernel_table.json`` —
    ``blocked_scan.resolve_scan_kernel`` consults it (sha-scoped) when an
    engine's ``scan_kernel="auto"``.  Off-TPU the fused arm runs the
    interpret/fallback path, so the table lands in a backend-suffixed file
    the production resolver never reads (and ``auto`` is gate-closed off
    hardware anyway) — the sweep still exercises both arms as CI smoke."""
    from raft_tpu.ops import blocked_scan as _scan

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    nq, nblocks, d = (16, 2, 64) if not on_tpu else (256, 8, 128)
    cands = [SCAN_CANDS[0]] if quick or not on_tpu else SCAN_CANDS
    ks = SCAN_K[:2] if quick or not on_tpu else SCAN_K
    key0 = jax.random.PRNGKey(1)
    entries = {}
    for family in SCAN_FAMILIES:
        exact = family == "ivf_flat"
        for c in cands:
            data = jax.random.normal(key0, (nblocks * c, d), jnp.float32)
            if not exact:  # recon tier scores a bf16 slab
                data = data.astype(jnp.bfloat16)
            q = jax.random.normal(key0, (nq, d), jnp.float32)
            if not exact:
                q = q.astype(jnp.bfloat16)
            qn = jnp.sum(q.astype(jnp.float32) ** 2, axis=1)
            norms = jnp.sum(data.astype(jnp.float32) ** 2, axis=1)
            rescore = _scan.l2_rescorer(data, norms, q, qn, "sqeuclidean",
                                        exact=exact)
            blocks_xs = jnp.arange(nblocks, dtype=jnp.int32)
            lane = jnp.arange(c, dtype=jnp.int32)

            # per-step gather from the shared slab — the engines' real
            # dataflow (a pre-broadcast [nblocks, nq, c, d] would be tens
            # of GB at production shapes)
            def gather(blk):
                vid = jnp.broadcast_to(blk * c + lane, (nq, c))
                return data[vid], norms[vid], vid

            for k in ks:
                def run_xla():
                    def score(blk):
                        vecs, base, vid = gather(blk)
                        dots = _scan.slab_dots(vecs[:, None], q,
                                               exact=exact)
                        return (base - 2.0 * dots.reshape(nq, c), vid)

                    return _scan.scan_topk(score, blocks_xs, nq, k)

                def run_fused():
                    def slab_step(blk):
                        vecs, base, vid = gather(blk)
                        return vecs, base, vid, vid

                    return _scan.scan_topk_fused(q, slab_step, blocks_xs,
                                                 rescore, nq, k)

                try:
                    t_f = _time(run_fused)
                except Exception as e:  # noqa: BLE001 — keep the xla arm
                    print(f"  fused {family} c={c} k={k}: failed "
                          f"({type(e).__name__})", file=sys.stderr)
                    t_f = float("inf")
                t_x = _time(run_xla)
                key = f"{family}:{c.bit_length()}:{k.bit_length()}"
                entries[key] = "fused" if t_f < t_x else "xla"
                print(f"scan {family:8s} cands={c:6d} k={k:4d} → "
                      f"{entries[key]} (xla {t_x * 1e3:.2f} ms, "
                      f"fused {t_f * 1e3:.2f} ms)")
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                       "raft_tpu", "ops", "_scan_kernel_table.json")
    if not on_tpu and "--force" not in sys.argv:
        out = out.replace(".json", f".{backend}.json")
        print(f"non-TPU backend: writing to {os.path.basename(out)} "
              f"(--force overrides)", file=sys.stderr)
    with open(out, "w") as f:
        json.dump({"kernel_sha": _scan.scan_kernel_sha(),
                   "backend": backend, "entries": entries},
                  f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {len(entries)} scan-kernel entries → "
          f"{os.path.normpath(out)}")


def main() -> None:
    quick = "--quick" in sys.argv
    rows_grid = [256, 2048] if quick else GRID_ROWS
    # quick mode keeps one short and one long column count (slicing the
    # grid would silently drop the long-row buckets that matter most)
    cols_grid = [1024, 16384] if quick else GRID_COLS
    sha = kernel_sha()
    backend = jax.default_backend()

    # resume checkpoint: the grid takes many fresh-compile minutes on a
    # tunnel that has wedged mid-step before — every decided bucket is
    # flushed immediately, and a re-run (queue attempt 2) skips buckets
    # already decided under the SAME backend + kernel sources
    ckpt_path = os.path.join(
        "/tmp", f"tune_select_k.{backend}.u{os.getuid()}.partial.json")
    table = {}
    try:
        with open(ckpt_path) as f:
            prior = json.load(f)
        if prior.get("backend") == backend and prior.get("kernel_sha") == sha:
            table = prior.get("table", {})
            print(f"resuming: {len(table)} buckets from checkpoint",
                  file=sys.stderr)
    except (OSError, ValueError):
        pass

    warned = []

    def flush_ckpt():
        try:
            with open(ckpt_path + ".tmp", "w") as f:
                json.dump({"backend": backend, "kernel_sha": sha,
                           "table": table}, f)
            os.replace(ckpt_path + ".tmp", ckpt_path)
        except OSError as e:
            # a silently-dead checkpoint would defeat the wedge-resume
            # feature exactly when it matters — warn once, keep tuning
            if not warned:
                warned.append(True)
                print(f"WARN: checkpoint flush failing ({e}); a mid-run "
                      f"kill will lose progress", file=sys.stderr)

    key0 = jax.random.PRNGKey(0)
    for rows in rows_grid:
        for cols in cols_grid:
            pending = [k for k in GRID_K
                       if k < cols and bucket_key(rows, cols, k) not in table]
            if not pending:
                continue
            x = jax.block_until_ready(
                jax.random.normal(key0, (rows, cols), jnp.float32))
            for k in pending:
                best_algo, best_t = None, float("inf")
                for algo in CANDIDATES:
                    if algo is SelectAlgo.kPartialBitonic and k > 64:
                        continue  # linear-in-k kernel: not competitive
                    try:
                        t = _time(lambda a=algo: select_k(x, k, algo=a))
                    except Exception as e:  # noqa: BLE001 — skip non-lowering algos
                        print(f"  {algo.name} rows={rows} cols={cols} k={k}: "
                              f"failed ({type(e).__name__})", file=sys.stderr)
                        continue
                    if t < best_t:
                        best_algo, best_t = algo, t
                if best_algo is None:
                    continue
                table[bucket_key(rows, cols, k)] = best_algo.value
                flush_ckpt()
                print(f"rows={rows:6d} cols={cols:7d} k={k:4d} → "
                      f"{best_algo.name} ({best_t * 1e3:.2f} ms)")
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                       "raft_tpu", "matrix", "_select_k_table.json")
    if jax.default_backend() != "tpu" and "--force" not in sys.argv:
        # an off-TPU run (CI smoke, contended-CPU drill) must never clobber
        # the production dispatch table the TPU search paths consult
        out = out.replace(".json", f".{jax.default_backend()}.json")
        print(f"non-TPU backend: writing to {os.path.basename(out)} "
              f"(--force overrides)", file=sys.stderr)
    with open(out, "w") as f:
        json.dump(table, f, indent=1, sort_keys=True)
    # provenance sidecar: NOT in the dispatch table (whose consumers —
    # dispatch, tests — treat every key as a b:l:k bucket)
    import datetime

    with open(out.replace(".json", ".meta.json"), "w") as f:
        json.dump({"backend": backend,
                   "date": datetime.date.today().isoformat(),
                   "kernel_sha": sha,
                   "n_entries": len(table)}, f)
        f.write("\n")
    try:
        os.remove(ckpt_path)  # spent: the final table supersedes it
    except OSError:
        pass
    print(f"wrote {len(table)} entries → {os.path.normpath(out)}")
    tune_fused_scan(quick)


if __name__ == "__main__":
    main()
