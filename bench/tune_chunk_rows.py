"""Offline chunk_rows tuning — pick the streaming-build chunk size per
``(family, dim)`` bucket by measurement, the same trained-heuristic
pattern as ``bench/tune_probe_block.py``.

The pipelined chunk engine produces a BIT-identical index for every
``chunk_rows`` (tests/test_chunked_builds.py), so this tuner compares
pure streaming wall-clock — no recall gate.  Small chunks pay dispatch
overhead per chunk; large chunks pay staging-buffer memory and (on TPU)
a longer exposed first-chunk copy.  Run on the target backend (real TPU
for production numbers):

    python bench/tune_chunk_rows.py [--quick] [--cpu]

Writes ``raft_tpu/neighbors/_chunk_rows_table.json`` keyed by
``family:dim.bit_length()`` — ``build_chunked``'s ``chunk_rows=0``
(auto) consults it via ``resolve_chunk_rows`` at call time; absent
entries fall back to ``DEFAULT_CHUNK_ROWS``.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR",
                      os.path.expanduser("~/.cache/raft_tpu_jax"))

import jax

from _platform import pin_backend

# MUST precede any backend use (see _platform.py: the axon plugin's
# sitecustomize overrides a bare JAX_PLATFORMS env var)
pin_backend(sys.argv)

import numpy as np

from _timing import sync, timeit as _time
from raft_tpu.neighbors import ivf_flat, ivf_pq
from raft_tpu.neighbors._packing import resolve_chunk_rows

ROWS, N_LISTS = 400_000, 256
QUICK_ROWS = 120_000
DIMS = [64, 96]
QUICK_DIMS = [64]
CANDIDATES = [4096, 8192, 16384, 32768, 65536, 131072]


def bucket_key(family: str, dim: int) -> str:
    """Must mirror ``resolve_chunk_rows``'s table key scheme exactly."""
    return f"{family}:{dim.bit_length()}"


def kernel_sha() -> str:
    """Hash of the chunk-engine sources the timings depend on — recorded
    in the sidecar (stale-table detection) and scoping the resume
    checkpoint."""
    import hashlib

    root = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")
    h = hashlib.sha256()
    for rel in ("raft_tpu/neighbors/ivf_flat.py",
                "raft_tpu/neighbors/ivf_pq.py",
                "raft_tpu/neighbors/_packing.py",
                "raft_tpu/cluster/kmeans.py",
                "raft_tpu/core/double_buffer.py"):
        with open(os.path.join(root, rel), "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def _stream_fn(family: str, x, chunk_rows: int):
    """Zero-arg streaming thunk over a shared trained quantizer (training
    is chunk_rows-independent and stays off the clock)."""
    n, d = x.shape
    if family == "ivf_flat":
        p = ivf_flat.IvfFlatIndexParams(
            n_lists=N_LISTS, kmeans_trainset_fraction=0.02,
            kmeans_n_iters=5, seed=0)
        cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
        cents = ivf_flat._coarse_train_chunked(x, p, n)
        sync(cents)
        return lambda: ivf_flat._stream_pipelined(
            x, cents, p, n, cap, chunk_rows, None, cents.dtype)
    p = ivf_pq.IvfPqIndexParams(
        n_lists=N_LISTS, pq_dim=16, kmeans_trainset_fraction=0.02,
        kmeans_n_iters=5, pq_kmeans_n_iters=5, seed=0)
    m = p.pq_dim
    cap = max(1, int(np.ceil(p.list_cap_ratio * n / p.n_lists)))
    cents, cbs = ivf_pq._pq_train_chunked(x, p, n, m, 1 << p.pq_bits)
    sync((cents, cbs))
    return lambda: ivf_pq._pq_stream_pipelined(
        x, cents, cbs, p, n, m, cap, chunk_rows, None)


def main() -> None:
    quick = "--quick" in sys.argv
    rows = QUICK_ROWS if quick else ROWS
    dims = QUICK_DIMS if quick else DIMS
    sha = kernel_sha()
    backend = jax.default_backend()

    # resume checkpoint: decided buckets flush immediately and a re-run
    # under the SAME backend + kernel sources skips them (tunnel-wedge
    # recovery, same story as tune_probe_block.py)
    ckpt_path = os.path.join(
        "/tmp", f"tune_chunk_rows.{backend}.u{os.getuid()}.partial.json")
    table: dict = {}
    timings: dict = {}
    try:
        with open(ckpt_path) as f:
            prior = json.load(f)
        if prior.get("backend") == backend and prior.get("kernel_sha") == sha:
            table = prior.get("table", {})
            timings = prior.get("timings", {})
            print(f"resuming: {len(table)} buckets from checkpoint",
                  file=sys.stderr)
    except (OSError, ValueError):
        pass

    warned = []

    def flush_ckpt():
        try:
            with open(ckpt_path + ".tmp", "w") as f:
                json.dump({"backend": backend, "kernel_sha": sha,
                           "table": table, "timings": timings}, f)
            os.replace(ckpt_path + ".tmp", ckpt_path)
        except OSError as e:
            if not warned:
                warned.append(True)
                print(f"WARN: checkpoint flush failing ({e}); a mid-run "
                      f"kill will lose progress", file=sys.stderr)

    rng = np.random.default_rng(0)
    for dim in dims:
        x = rng.standard_normal((rows, dim)).astype(np.float32)
        for family in ("ivf_flat", "ivf_pq"):
            key = bucket_key(family, dim)
            if key in table:
                continue
            best_c, best_t, curve = None, float("inf"), {}
            for cr in CANDIDATES:
                if cr > rows:
                    continue
                t = _time(_stream_fn(family, x, cr))
                curve[str(cr)] = t
                if t < best_t:
                    best_c, best_t = cr, t
            table[key] = best_c
            timings[key] = {"rows": rows, "dim": dim, "n_lists": N_LISTS,
                            "curve_s": curve}
            flush_ckpt()
            print(f"{family:9s} dim={dim:4d} → chunk_rows={best_c} "
                  f"({rows / best_t:,.0f} rows/s)")
        del x

    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                       "raft_tpu", "neighbors", "_chunk_rows_table.json")
    if backend != "tpu" and "--force" not in sys.argv:
        # an off-TPU run must never clobber the table the TPU build
        # paths consult (same rule as the probe_block tuner)
        out = out.replace(".json", f".{backend}.json")
        print(f"non-TPU backend: writing to {os.path.basename(out)} "
              f"(--force overrides)", file=sys.stderr)
    with open(out, "w") as f:
        json.dump(table, f, indent=1, sort_keys=True)

    import datetime

    with open(out.replace(".json", ".meta.json"), "w") as f:
        json.dump({"backend": backend,
                   "date": datetime.date.today().isoformat(),
                   "kernel_sha": sha,
                   "rows": rows,
                   "n_entries": len(table)}, f)
        f.write("\n")
    try:
        os.remove(ckpt_path)  # spent: the final table supersedes it
    except OSError:
        pass
    print(f"wrote {len(table)} entries → {os.path.normpath(out)}")
    # the auto path must be able to see what we just measured
    r = resolve_chunk_rows(0, 10 ** 9, dims[0], "ivf_flat")
    assert r >= 1


if __name__ == "__main__":
    main()
